"""Traffic capture plane (round 17): pinned on-disk struct layout,
commit-word crash safety (a torn/uncommitted slot is skipped, never
fatal), deterministic seeded sampling, size-bounded rotation with
segment pruning, the cross-member merge readers, the finish_request
once-only completion latch, and replay schedule fidelity against a
local HTTP stub.
"""
from __future__ import annotations

import json
import struct
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from language_detector_tpu import capture, telemetry
from language_detector_tpu.capture import (COMMIT, FILE_HDR, RECORD,
                                           SLOT_BYTES, CaptureWriter,
                                           merge_captures, read_capture,
                                           record_from, size_bucket,
                                           tenant_hash)
from language_detector_tpu.telemetry import Trace


# -- format pins -------------------------------------------------------------


def test_struct_sizes_pinned():
    """The on-disk format cannot drift silently: these numbers are the
    wire contract every sealed segment on every machine depends on."""
    assert FILE_HDR.size == 36
    assert COMMIT.size == 4
    assert RECORD.size == 54
    assert SLOT_BYTES == 58
    assert capture.VERSION == 1


def test_tenant_hash_stable_and_anonymous():
    h = tenant_hash("acme")
    assert h == tenant_hash("acme")          # stable across calls
    assert h != tenant_hash("acme2")
    assert tenant_hash(None) == tenant_hash("default")
    assert 0 < h < 2 ** 64
    # the raw tenant string is not recoverable from the record
    assert "acme" not in f"t{h:016x}"


def test_size_bucket_log2():
    assert size_bucket(0) == 0
    assert size_bucket(1) == 1
    assert size_bucket(900) == 10            # 512 < 900 <= 1024
    assert size_bucket(1 << 20) == 21


# -- record round-trip -------------------------------------------------------


def _trace(tenant="acme"):
    tr = Trace()
    tr.tenant = tenant
    tr.add("parse", tr.t0, tr.t0 + 0.001)
    tr.add("detect", tr.t0 + 0.001, tr.t0 + 0.005)
    tr.add("encode", tr.t0 + 0.005, tr.t0 + 0.006)
    return tr


def test_record_roundtrip(tmp_path):
    w = CaptureWriter(str(tmp_path), ring_records=64, sample=1.0)
    try:
        tr = _trace()
        meta = {"front": "sync", "status": 200, "docs": 3,
                "bytes": 900, "priority": True, "cache_bits": 0b101}
        assert w.append(record_from(tr, meta, 6.25))
        recs = read_capture(str(tmp_path))
        assert len(recs) == 1
        r = recs[0]
        assert r["tenant"] == f"t{tenant_hash('acme'):016x}"
        assert r["docs"] == 3
        assert r["size_bucket"] == 10
        assert r["approx_bytes"] == 512
        assert r["lane"] == "tcp"
        assert r["verdict"] == "ok"
        assert r["status"] == 200
        assert r["priority"] and not r["shed"]
        assert r["cache_bits"] == 0b101
        assert r["total_ms"] == pytest.approx(6.25, abs=0.01)
        assert r["parse_ms"] == pytest.approx(1.0, abs=0.1)
        assert r["detect_ms"] == pytest.approx(4.0, abs=0.1)
    finally:
        w.close()


def test_verdict_and_lane_mapping(tmp_path):
    w = CaptureWriter(str(tmp_path), ring_records=64, sample=1.0)
    try:
        cases = [
            ({"front": "uds", "status": 429, "shed": True}, "uds",
             "shed"),
            ({"front": "shm", "status": 500}, "shm", "error"),
            ({"front": "aio", "status": 504, "timeout": True}, "tcp",
             "timeout"),
            ({"front": "sync", "status": 400}, "tcp", "invalid"),
        ]
        for meta, _, _ in cases:
            w.append(record_from(_trace(), meta, 1.0))
        recs = read_capture(str(tmp_path))
        assert [(r["lane"], r["verdict"]) for r in recs] == \
            [(lane, verdict) for _, lane, verdict in cases]
        assert recs[0]["shed"]
    finally:
        w.close()


# -- crash safety ------------------------------------------------------------


def test_torn_commit_word_skips_one_slot(tmp_path):
    """The crash-safety contract: zeroing (or garbling) one slot's
    commit word makes exactly that record invisible — the payload
    bytes still sitting in the map never surface."""
    w = CaptureWriter(str(tmp_path), ring_records=64, sample=1.0)
    try:
        for i in range(3):
            w.append(record_from(_trace(f"t{i}"), {"front": "sync",
                                                   "status": 200}, 1.0))
        off = FILE_HDR.size + 1 * SLOT_BYTES
        w.mm[off:off + COMMIT.size] = struct.pack("<I", 0)   # torn
        recs = read_capture(str(tmp_path))
        assert len(recs) == 2
        assert {r["tenant_hash"] for r in recs} == \
            {tenant_hash("t0"), tenant_hash("t2")}
        # a wrong (stale-generation) commit value is equally invisible
        w.mm[off:off + COMMIT.size] = struct.pack("<I", 99)
        assert len(read_capture(str(tmp_path))) == 2
    finally:
        w.close()


def test_abandoned_ring_is_readable(tmp_path):
    """A SIGKILLed writer leaves only its ring file; the committed
    records in it are harvested without any shutdown handshake."""
    w = CaptureWriter(str(tmp_path), ring_records=64, sample=1.0)
    for i in range(5):
        w.append(record_from(_trace(), {"front": "sync",
                                        "status": 200}, 1.0))
    # no close(), no seal: read straight from the abandoned file
    assert len(read_capture(str(tmp_path))) == 5
    w.close()


def test_reader_rejects_bad_files(tmp_path):
    (tmp_path / "segment-1-000001.cap").write_bytes(b"junkjunkjunk")
    bad_ver = FILE_HDR.pack(capture.RING_MAGIC, 99, 16, RECORD.size,
                            1, 0.0, 0)
    (tmp_path / "capture-2.ring").write_bytes(bad_ver)
    with pytest.raises(ValueError):
        capture._read_file(str(tmp_path / "segment-1-000001.cap"))
    with pytest.raises(ValueError):
        capture._read_file(str(tmp_path / "capture-2.ring"))
    # the directory readers skip what they cannot parse
    assert read_capture(str(tmp_path)) == []
    assert merge_captures(str(tmp_path)) == []


# -- sampling ----------------------------------------------------------------


def test_sampling_deterministic_under_seed(tmp_path):
    """LDT_CAPTURE_SAMPLE keeps a seeded-RNG-deterministic subset: two
    writers with the same seed keep exactly the same records."""
    masks = []
    for sub in ("a", "b"):
        w = CaptureWriter(str(tmp_path / sub), ring_records=256,
                          sample=0.5, seed=7)
        try:
            mask = [w.append(record_from(_trace(), {"front": "sync",
                                                    "status": 200},
                                         1.0))
                    for _ in range(100)]
        finally:
            w.close()
        masks.append(mask)
    assert masks[0] == masks[1]
    kept = sum(masks[0])
    assert 0 < kept < 100                    # it actually sampled
    assert masks[0].count(False) == 100 - kept


def test_sample_one_keeps_everything(tmp_path):
    w = CaptureWriter(str(tmp_path), ring_records=64, sample=1.0,
                      seed=3)
    try:
        assert all(w.append(record_from(_trace(), {"front": "sync",
                                                   "status": 200}, 1.0))
                   for _ in range(20))
        assert w.stats()["sampled_out"] == 0
    finally:
        w.close()


# -- rotation ----------------------------------------------------------------


def test_rotation_seals_and_prunes(tmp_path):
    w = CaptureWriter(str(tmp_path), ring_records=16, sample=1.0,
                      max_segments=2)
    try:
        for i in range(16 * 4 + 5):          # 4 seals + 5 in the ring
            w.append(record_from(_trace(f"t{i}"), {"front": "sync",
                                                   "status": 200}, 1.0))
        st = w.stats()
        assert st["segments_sealed"] == 4
        assert st["ring_occupancy"] == 5
        assert st["records_total"] == 16 * 4 + 5
        segs = sorted(tmp_path.glob("segment-*.cap"))
        assert len(segs) == 2                # pruned to max_segments
        # the kept segments are the newest two
        assert [s.name.split("-")[-1] for s in segs] == \
            ["000003.cap", "000004.cap"]
        # no tmp litter from the tmp+rename publication
        assert list(tmp_path.glob("*.tmp.*")) == []
        # readable total: 2 kept segments + the live ring
        assert len(read_capture(str(tmp_path))) == 16 * 2 + 5
    finally:
        w.close()


def test_merge_captures_orders_across_members(tmp_path):
    """The fleet writes each member under m<slot>/; the merge joins
    them into one arrival-ordered stream via the anchor pair."""
    writers = []
    for slot in (0, 1):
        w = CaptureWriter(str(tmp_path / f"m{slot}"), ring_records=64,
                          sample=1.0)
        writers.append(w)
    try:
        # interleave arrivals across members by nudging trace.t0
        for i in range(10):
            tr = _trace(f"t{i}")
            tr.t0 = tr.t0 + i * 0.010        # strictly increasing
            writers[i % 2].append(record_from(tr, {"front": "sync",
                                                   "status": 200}, 1.0))
        merged = merge_captures(str(tmp_path))
        assert len(merged) == 10
        arrivals = [r["arrival_ns"] for r in merged]
        assert arrivals == sorted(arrivals)
        tenants = [r["tenant_hash"] for r in merged]
        assert tenants == [tenant_hash(f"t{i}") for i in range(10)]
    finally:
        for w in writers:
            w.close()


def test_summarize(tmp_path):
    w = CaptureWriter(str(tmp_path / "m0"), ring_records=64, sample=1.0)
    try:
        for i in range(6):
            w.append(record_from(
                _trace("hot" if i < 4 else "cold"),
                {"front": "sync", "status": 200 if i else 429,
                 "shed": i == 0}, 1.0))
        s = capture.summarize(str(tmp_path))
        assert s["records"] == 6 and s["rings"] == 1
        assert s["tenants"] == 2 and s["sheds"] == 1
        assert s["top_tenants"][0]["records"] == 4
        assert s["lanes"] == {"tcp": 6}
        assert s["statuses"] == {"200": 5, "429": 1}
    finally:
        w.close()


# -- module hook & counters --------------------------------------------------


def test_observe_counters_and_segment_inc(tmp_path, monkeypatch):
    telemetry.REGISTRY.reset()
    w = CaptureWriter(str(tmp_path), ring_records=16, sample=1.0)
    monkeypatch.setattr(capture, "WRITER", w)
    try:
        for _ in range(17):                  # crosses one seal
            capture.observe(_trace(), {"front": "sync", "status": 200},
                            1.0)
        reg = telemetry.REGISTRY
        assert reg.counter_value("ldt_capture_records_total") == 17
        assert reg.counter_value("ldt_capture_segments_total") == 1
        assert reg.counter_value("ldt_capture_sampled_out_total") == 0
        assert capture.stats()["segments_sealed"] == 1
    finally:
        monkeypatch.setattr(capture, "WRITER", None)
        w.close()
        telemetry.REGISTRY.reset()


def test_init_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv("LDT_CAPTURE_DIR", str(tmp_path))
    monkeypatch.setattr(capture, "WRITER", None)
    try:
        w = capture.init_from_env()
        assert w is not None
        assert capture.init_from_env() is w  # idempotent
        import os
        assert os.path.isfile(w.path)
        assert w.path.startswith(str(tmp_path))
    finally:
        capture.reset_for_tests()


def test_finish_request_counts_exactly_once(tmp_path, monkeypatch):
    """Regression: a handler that unwinds through two finish sites
    (shed answered 429, then the outer 504 path fires again on the
    same trace) must count ONCE in the histogram, the capture plane,
    and the SLO engine — the trace's completion latch is the single
    authoritative completion path."""
    from language_detector_tpu import slo
    telemetry.REGISTRY.reset()
    w = CaptureWriter(str(tmp_path), ring_records=64, sample=1.0)
    eng = slo.SloEngine(slo.parse_spec("p99_ms=1000,err_pct=1"),
                        min_events=1)
    monkeypatch.setattr(capture, "WRITER", w)
    monkeypatch.setattr(slo, "ENGINE", eng)
    try:
        tr = _trace()
        telemetry.finish_request(tr, meta={"front": "sync",
                                           "status": 429, "shed": True})
        # the second unwind path fires on the SAME trace
        telemetry.finish_request(tr, meta={"front": "sync",
                                           "status": 504})
        h = telemetry.REGISTRY.histogram("ldt_request_latency_ms")
        assert h.snapshot()[2] == 1          # histogram count
        assert w.stats()["records_total"] == 1
        assert eng.stats()["observed"] == 1
        # the FIRST completion wins: the record says shed/429, not 504
        recs = read_capture(str(tmp_path))
        assert len(recs) == 1
        assert recs[0]["status"] == 429 and recs[0]["verdict"] == "shed"
        assert telemetry.REGISTRY.counter_value(
            "ldt_slo_events_total", result="shed") == 1
    finally:
        monkeypatch.setattr(capture, "WRITER", None)
        monkeypatch.setattr(slo, "ENGINE", None)
        w.close()
        telemetry.REGISTRY.reset()


# -- replay fidelity ---------------------------------------------------------


class _StubHandler(BaseHTTPRequestHandler):
    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        self.rfile.read(n)
        body = json.dumps({"ok": True}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


def test_replay_reproduces_schedule():
    """A 200-request synthetic burst replayed against a trivial local
    stub lands its p95 send-time skew within 10% of the recorded span
    — the acceptance bound `bench.py --replay` gates on."""
    import bench
    records = bench.synth_capture_records(n=200, tenants=8,
                                          rate_rps=150.0, seed=11)
    assert len(records) == 200
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _StubHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        out = bench.replay_records(records, srv.server_address[1],
                                   speedup=1.0, clients=8)
    finally:
        srv.shutdown()
        srv.server_close()
    assert out["requests"] == 200
    assert out["completed"] == 200
    assert out["counts"]["drop"] == 0
    assert out["counts"]["ok"] == 200
    assert out["schedule"]["skew_frac_p95"] <= 0.10
    # the zipf skew showed up: the hottest tenant dominates
    top = max(out["tenants"].values(), key=lambda d: d["requests"])
    assert top["requests"] > 200 / 8


def test_replay_synth_payloads_deterministic():
    import bench
    a = bench._synth_replay_text(12345, 3, 256)
    b = bench._synth_replay_text(12345, 3, 256)
    c = bench._synth_replay_text(12345, 4, 256)
    assert a == b
    assert len(a.encode()) >= 256
    # seq cycles mod dup_modulo: seq 3 and 3+16 are the same document
    assert bench._synth_replay_text(12345, 3 + 16, 256) == a
    assert c != a
