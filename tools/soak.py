#!/usr/bin/env python3
"""End-to-round divergence soak: every engine path vs the scalar oracle.

Runs thousands of randomized fuzz documents (the construction soup from
tests/test_batch_agreement.py) through each production path and counts
exact-result mismatches against the scalar engine — the strongest
whole-system check the repo has, used as the round-end stability bake:

  plain    detect_batch, full ScalarResult tuple equality
  codes    multi-slice detect_codes (ragged slices force the deferred
           cross-slice gate-retry path)
  hints    TLD + content-language hints
  html     is_plain_text=False with rotating lang= attributes
  vectors  return_chunks: per-range vector AND summary equality
  c-abi    raw ctypes detect_language_n vs the device engine

Exits non-zero on any mismatch. Usage: python3 tools/soak.py [scale]
(scale multiplies the per-path document counts; default 1 ~ 4K docs,
a few minutes on the single-core host).
"""
from __future__ import annotations

import ctypes
import random
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tests"))

from language_detector_tpu import enable_jit_cache  # noqa: E402

enable_jit_cache()


def _fuzz_docs(n: int, seed: int) -> list:
    """test_batch_agreement's construction soup over the golden corpus
    when available, else over bench.py's self-contained corpus — the
    soak must run (and the new bucket/dedup passes must exercise) on
    hosts without the reference snapshot."""
    import random as _random

    from test_batch_agreement import _fill_fuzz_docs, _golden_texts
    try:
        texts = _golden_texts()
    except BaseException:  # pytest.skip escalates outside a test run
        texts = []
    if not texts:
        import bench
        base = bench.make_corpus(64)
        texts = [" ".join(base[i:i + 12]) for i in range(0, 64, 4)]
    rng = _random.Random(seed)
    docs: list = []
    _fill_fuzz_docs(docs, rng, texts, n)
    return docs


def main(scale: int = 1) -> int:
    from language_detector_tpu import native
    from language_detector_tpu.engine_scalar import detect_scalar
    from language_detector_tpu.hints import CLDHints
    from language_detector_tpu.models.ngram import NgramBatchEngine
    from language_detector_tpu.registry import registry
    from language_detector_tpu.tables import load_tables

    eng = NgramBatchEngine()
    failures = 0

    def stuple(r):
        return (r.summary_lang, list(r.language3), list(r.percent3),
                r.text_bytes, r.is_reliable)

    def report(name, bad, n):
        nonlocal failures
        failures += bad
        print(f"{name:28s} {n - bad}/{n} exact", flush=True)

    n = 2048 * scale
    docs = _fuzz_docs(n, seed=99001)
    got = eng.detect_batch(docs)
    report("plain detect_batch", sum(
        1 for t, g in zip(docs, got)
        if stuple(g) != stuple(detect_scalar(t, eng.tables, eng.reg, 0))),
        n)

    codes = eng.detect_codes(docs, batch_size=257)
    report("codes multi-slice+retry", sum(
        1 for g, c in zip(got, codes)
        if eng.reg.code(g.summary_lang) != c), n)

    nh = 256 * scale
    hdocs = _fuzz_docs(nh, seed=99002)
    for hint in (CLDHints(tld_hint="fr"),
                 CLDHints(content_language_hint="de,en")):
        hgot = eng.detect_batch(hdocs, hints=hint)
        report(f"hints {hint.tld_hint or hint.content_language_hint}",
               sum(1 for t, g in zip(hdocs, hgot)
                   if stuple(g) != stuple(detect_scalar(
                       t, eng.tables, eng.reg, 0, hints=hint))), nh)

    rng = random.Random(99003)
    html_docs = [
        f"<html lang='{rng.choice(['fr', 'ja', '', 'de'])}'>"
        f"<p>{d[:400]}</p></html>"
        for d in _fuzz_docs(nh, seed=99004)]
    hg = eng.detect_batch(html_docs, is_plain_text=False)
    report("html", sum(
        1 for t, g in zip(html_docs, hg)
        if stuple(g) != stuple(detect_scalar(
            t, eng.tables, eng.reg, 0, is_plain_text=False))), nh)

    nv = 192 * scale
    vdocs = _fuzz_docs(nv, seed=99005)
    vg = eng.detect_batch(vdocs, return_chunks=True)
    vbad = 0
    for t, g in zip(vdocs, vg):
        w = detect_scalar(t, eng.tables, eng.reg, 0, want_chunks=True)
        gch = [(c.offset, c.bytes, c.lang1) for c in (g.chunks or [])]
        wch = [(c.offset, c.bytes, c.lang1) for c in (w.chunks or [])]
        if gch != wch or g.summary_lang != w.summary_lang:
            vbad += 1
    report("chunk vectors", vbad, nv)

    native.ensure_init(load_tables(), registry)
    lib = ctypes.CDLL(str(Path(native.__file__).parent /
                          "libldtpack.so"))
    lib.detect_language_n.restype = ctypes.c_char_p
    lib.detect_language_n.argtypes = [ctypes.c_char_p, ctypes.c_int32]
    nc = 1024 * scale
    cdocs = _fuzz_docs(nc, seed=99010)
    cwant = eng.detect_codes(cdocs, batch_size=16384)
    cbad = 0
    for t, w in zip(cdocs, cwant):
        enc = t.encode("utf-8", "surrogatepass")
        if lib.detect_language_n(enc, len(enc)).decode() != w:
            cbad += 1
    report("raw C ABI", cbad, nc)

    # bucket boundaries: docs whose length straddles each slot-budget
    # tier (length m-1 / m / m+1 at every boundary) must route to
    # adjacent shape lanes with identical results. Instance overrides
    # force the tiered scheduler + retry lane at soak batch sizes.
    from language_detector_tpu.preprocess.pack import (SLOT_TIER_BUDGETS,
                                                       tier_max_chars)
    src = " ".join(_fuzz_docs(48, seed=99020))
    while len(src) < tier_max_chars(len(SLOT_TIER_BUDGETS) - 1) + 4096:
        src += " " + src
    bdocs = []
    for k in range(len(SLOT_TIER_BUDGETS)):
        m = tier_max_chars(k)
        for i in range(8 * scale):
            for delta in (-1, 0, 1):
                start = (i * 241) % 1024
                bdocs.append(src[start:start + m + delta])
    bdocs += _fuzz_docs(64 * scale, seed=99022)
    eng.TIER_MIN_DOCS = 16
    eng.RETRY_LANE_MIN = 4
    eng.TIER_COALESCE_MIN = 1
    try:
        bg = eng.detect_many(bdocs, batch_size=64)
        report("bucket boundaries", sum(
            1 for t, g in zip(bdocs, bg)
            if stuple(g) != stuple(detect_scalar(t, eng.tables, eng.reg,
                                                 0))), len(bdocs))

        # dedup + result cache: heavy duplication through the batched
        # path, then twice through a cache-enabled batcher — every
        # repeat (engine dedup AND LRU hit) must answer the oracle
        import random as _random
        uniq = _fuzz_docs(64 * scale, seed=99021)
        rngd = _random.Random(99023)
        ddocs = [uniq[rngd.randrange(len(uniq))]
                 for _ in range(256 * scale)]
        want = {t: stuple(detect_scalar(t, eng.tables, eng.reg, 0))
                for t in set(ddocs)}
        dg = eng.detect_many(ddocs, batch_size=64)
        report("dedup repeats", sum(
            1 for t, g in zip(ddocs, dg) if stuple(g) != want[t]),
            len(ddocs))

        from language_detector_tpu.service.batcher import Batcher
        want_codes = {t: registry.code(detect_scalar(
            t, eng.tables, eng.reg, 0).summary_lang)
            for t in set(ddocs)}
        bat = Batcher(lambda ts: eng.detect_codes(ts, batch_size=128),
                      cache_bytes=8 << 20)
        try:
            bad = 0
            for _pass in range(2):  # second pass serves from the cache
                got_codes = bat.submit(ddocs).result(timeout=600)
                bad += sum(1 for t, c in zip(ddocs, got_codes)
                           if want_codes[t] != c)
            cs = bat.cache_stats()
        finally:
            bat.close()
        report("cache hits", bad, 2 * len(ddocs))
        print(f"{'cache hit rate':28s} {cs['hit_rate']:.3f} "
              f"({cs['hits']} hits)", flush=True)
        if cs["hits"] == 0:
            failures += 1
            print("cache hit soak: zero hits (cache inert?)")
    finally:
        del eng.TIER_MIN_DOCS, eng.RETRY_LANE_MIN, eng.TIER_COALESCE_MIN

    print("SOAK", "CLEAN" if failures == 0 else f"FAILED ({failures})")
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    sys.exit(main(*(int(a) for a in sys.argv[1:])))
