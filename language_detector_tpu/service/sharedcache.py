"""Fleet-shared result cache: an mmap seqlock table every worker shares.

Round 16 (ROADMAP item 5). Each SO_REUSEPORT fleet member keeps a
private ResultCache, so a retweet storm re-scores the same hot document
once per worker. This module adds the L2 those workers share: one
fixed-geometry mmap file (LDT_RESULT_CACHE_SHM_MB, normally under
/dev/shm) holding an open-addressed table of
(doc-hash -> packed result fragment), readable and writable by every
process with zero locks, built on the same publish-order discipline as
the shmring ingest plane (service/shmring.py):

  slot layout (SLOT_BYTES, 64-byte aligned)
      u32  seq       seqlock word: even = published/free, odd = a
                     writer is inside (or died inside) the slot;
                     written LAST on publish
      u32  crc       crc32 over (epoch, key, vlen, payload) as written
      u64  epoch     artifact-epoch hash: a result is only legal
                     against the tables that produced it
      16s  key       sha256(hints_key, normalized text), truncated
      u32  vlen      payload length; 0 = free slot
      u32  (pad)
      ...  payload   the result fragment (ISO code string, utf-8)

Write protocol (single-writer-per-slot, CAS-style claim): read an even
seq s, publish s+1 (claim), write fields, publish s+2 — the seq bump is
the commit point, exactly shmring's state-word-last rule. Two writers
racing one slot both see s and both write: the final even seq publishes
interleaved bytes, and the CRC — computed by each writer over its OWN
data — then refuses the slot on read. The race loses a cache fill,
never correctness. A writer killed mid-slot leaves seq odd: readers and
the free-slot scan skip it forever, and the displacement-eviction path
adopts the stale odd seq as its claim, so the slot heals on the next
overwrite instead of leaking.

Read protocol (torn-read-safe): seq1 even -> copy fields -> seq2 ==
seq1 -> epoch matches -> key matches -> CRC verifies, else miss. Every
failure mode (absent, epoch-stale, torn, corrupt) is just a miss; the
shared tier can lose entries but can never serve a wrong or stale one.

Epoch flush on swap: set_epoch() re-keys the reader check immediately
(old-epoch entries are unreachable the moment the local epoch word
changes) and then sweeps the table freeing stale-epoch slots, counting
``ldt_shared_cache_epoch_flush_total`` — so a mid-burst artifact swap
yields zero stale hits by construction, and the capacity comes back.

Geometry is fixed at file creation (header wins over a later knob
change); creation is flock-serialized so N members starting at once
initialize the file exactly once.
"""
from __future__ import annotations

import hashlib
import mmap
import os
import struct
import zlib

from .. import knobs, telemetry
from ..locks import make_lock

MAGIC = b"LDTSHC1\n"
VERSION = 1
_HEADER = struct.Struct("<8sIII")   # magic, version, slot_count, slot_bytes
HEADER_BYTES = 64
SLOT_BYTES = 128
_SLOT_HDR = struct.Struct("<IIQ16sII")  # seq, crc, epoch, key, vlen, pad
SLOT_HDR_BYTES = _SLOT_HDR.size          # 40
PAYLOAD_CAP = SLOT_BYTES - SLOT_HDR_BYTES
PROBE_WINDOW = 8

_U32 = struct.Struct("<I")

# pinned shm geometry: a drive-by field edit must fail at import, not
# hand torn slots to every attached worker
# (tools/lint/layout_registry.py declares the same widths)
assert _HEADER.size == 20
assert _SLOT_HDR.size == 40
assert _U32.size == 4


def _key_hash(key) -> bytes:
    """16-byte content hash of a (hints_key, text) cache key. repr of
    the hints tuple is stable across processes for the str/int/tuple
    values the service builds them from."""
    return hashlib.sha256(repr(key).encode(
        "utf-8", "surrogatepass")).digest()[:16]


def _epoch_hash(epoch) -> int:
    """u64 epoch word from the artifact epoch object (digest string,
    swap counter string, or the initial None)."""
    return int.from_bytes(
        hashlib.sha256(repr(epoch).encode()).digest()[:8], "little")


class SharedResultCache:
    """One process's view of the shared table. Thread-safe: the mmap
    protocol is lock-free by design and the per-process stats counters
    take their own lock."""

    def __init__(self, path: str, max_bytes: int):
        self.path = path
        self._lock = make_lock("sharedcache.stats")
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.epoch_flushes = 0
        self._epoch_word = _epoch_hash(None)
        self._mm, self.slot_count = self._attach(path, max_bytes)

    @staticmethod
    def _attach(path: str, max_bytes: int):
        slots = max(PROBE_WINDOW,
                    (max_bytes - HEADER_BYTES) // SLOT_BYTES)
        size = HEADER_BYTES + slots * SLOT_BYTES
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            # creation race: first member in initializes, the rest
            # adopt whatever geometry the header already declares
            import fcntl
            fcntl.flock(fd, fcntl.LOCK_EX)
            try:
                head = os.pread(fd, HEADER_BYTES, 0)
                init = len(head) < _HEADER.size or \
                    head[:len(MAGIC)] != MAGIC
                if init:
                    os.ftruncate(fd, 0)
                    os.ftruncate(fd, size)
                    os.pwrite(fd, _HEADER.pack(MAGIC, VERSION, slots,
                                               SLOT_BYTES), 0)
                else:
                    _, ver, slots, slot_bytes = _HEADER.unpack(
                        head[:_HEADER.size])
                    if ver != VERSION or slot_bytes != SLOT_BYTES:
                        raise RuntimeError(
                            f"shared cache {path}: incompatible layout "
                            f"(version {ver}, slot {slot_bytes}B) — "
                            f"remove the file or point "
                            f"LDT_SHARED_CACHE_FILE elsewhere")
                    size = HEADER_BYTES + slots * SLOT_BYTES
            finally:
                fcntl.flock(fd, fcntl.LOCK_UN)
            mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        return mm, slots

    # -- slot access ---------------------------------------------------

    def _off(self, idx: int) -> int:
        return HEADER_BYTES + idx * SLOT_BYTES

    def _seq(self, off: int) -> int:
        return _U32.unpack_from(self._mm, off)[0]

    @staticmethod
    def _crc(epoch: int, key: bytes, payload: bytes) -> int:
        return zlib.crc32(struct.pack("<Q16sI", epoch, key,
                                      len(payload)) + payload)

    def set_epoch(self, epoch) -> None:
        """Swap to a new artifact epoch: rebind the local epoch word
        (stale entries become unreachable instantly), then sweep the
        table freeing slots the old artifact wrote so the capacity is
        reusable. Concurrent sweeps from several members are benign —
        freeing a free slot is a no-op."""
        word = _epoch_hash(epoch)
        if word == self._epoch_word:
            return
        self._epoch_word = word
        mm = self._mm
        flushed = 0
        for idx in range(self.slot_count):
            off = self._off(idx)
            s = self._seq(off)
            if s & 1:
                continue  # dead/active writer; eviction will heal it
            _, _, slot_epoch, _, vlen, _ = _SLOT_HDR.unpack_from(
                mm, off)
            if vlen == 0 or slot_epoch == word:
                continue
            # claim, clear, publish — the standard write protocol with
            # an empty body
            _U32.pack_into(mm, off, s + 1)
            _SLOT_HDR.pack_into(mm, off, s + 1, 0, 0, b"\0" * 16, 0, 0)
            _U32.pack_into(mm, off, s + 2)
            flushed += 1
        if flushed:
            with self._lock:
                self.epoch_flushes += flushed
            telemetry.REGISTRY.counter_inc(
                "ldt_shared_cache_epoch_flush_total", flushed)

    def get(self, key):
        """The published value for `key` under the current epoch, or
        None. Torn, stale, and corrupt slots all read as a miss."""
        kh = _key_hash(key)
        base = int.from_bytes(kh[:8], "little") % self.slot_count
        mm = self._mm
        for i in range(PROBE_WINDOW):
            off = self._off((base + i) % self.slot_count)
            seq1 = self._seq(off)
            if seq1 & 1:
                continue
            _, crc, epoch, skey, vlen, _ = _SLOT_HDR.unpack_from(
                mm, off)
            if skey != kh or vlen == 0:
                continue
            if vlen > PAYLOAD_CAP:
                continue  # corrupt length: never slice garbage
            payload = bytes(mm[off + SLOT_HDR_BYTES:
                               off + SLOT_HDR_BYTES + vlen])
            if self._seq(off) != seq1:
                continue  # torn read: a writer moved under us
            if epoch != self._epoch_word:
                continue
            if self._crc(epoch, skey, payload) != crc:
                continue
            with self._lock:
                self.hits += 1
            telemetry.REGISTRY.counter_inc(
                "ldt_shared_cache_hits_total")
            try:
                return payload.decode("utf-8")
            except UnicodeDecodeError:
                return None
        with self._lock:
            self.misses += 1
        telemetry.REGISTRY.counter_inc("ldt_shared_cache_misses_total")
        return None

    def put(self, key, value: str) -> None:
        """Publish a result under the current epoch. Best-effort: an
        oversized value, a full probe window, or a lost write race cost
        a future cache fill, nothing else."""
        payload = value.encode("utf-8", "surrogatepass")
        if len(payload) > PAYLOAD_CAP:
            return
        kh = _key_hash(key)
        base = int.from_bytes(kh[:8], "little") % self.slot_count
        mm = self._mm
        target = None
        evict = False
        for i in range(PROBE_WINDOW):
            off = self._off((base + i) % self.slot_count)
            s = self._seq(off)
            if s & 1:
                continue
            _, _, epoch, skey, vlen, _ = _SLOT_HDR.unpack_from(mm, off)
            if skey == kh and epoch == self._epoch_word and vlen:
                return  # already published (results are deterministic)
            if vlen == 0 and target is None:
                target = off
            elif epoch != self._epoch_word and target is None:
                # stale-epoch slot: as good as free
                target = off
        if target is None:
            # window full of live same-epoch entries (or dead writers):
            # deterministic displacement — the key picks its victim, so
            # racing writers of one key agree on the slot
            evict = True
            target = self._off((base + kh[8] % PROBE_WINDOW)
                               % self.slot_count)
        off = target
        s = self._seq(off)
        # claim: odd means a writer died here (or is live — then our
        # write loses to its CRC, see module docstring); adopt the odd
        # seq as the claim so dead slots heal instead of leaking
        writing = s + 1 if (s & 1) == 0 else s
        _U32.pack_into(mm, off, writing)
        crc = self._crc(self._epoch_word, kh, payload)
        _SLOT_HDR.pack_into(mm, off, writing, crc, self._epoch_word,
                            kh, len(payload), 0)
        mm[off + SLOT_HDR_BYTES:off + SLOT_HDR_BYTES + len(payload)] \
            = payload
        _U32.pack_into(mm, off, writing + 1)
        if evict:
            with self._lock:
                self.evictions += 1
            telemetry.REGISTRY.counter_inc(
                "ldt_shared_cache_evictions_total")

    def stats(self) -> dict:
        with self._lock:
            hits, misses = self.hits, self.misses
            evictions = self.evictions
            flushes = self.epoch_flushes
        total = hits + misses
        return {"path": self.path, "slots": self.slot_count,
                "slot_bytes": SLOT_BYTES, "hits": hits,
                "misses": misses, "evictions": evictions,
                "epoch_flushes": flushes,
                "hit_rate": hits / total if total else 0.0}

    def close(self) -> None:
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass


def default_path() -> str:
    explicit = knobs.get_str("LDT_SHARED_CACHE_FILE")
    if explicit:
        return explicit
    base = knobs.get_str("LDT_SHM_DIR")
    if not base:
        base = "/dev/shm" if os.path.isdir("/dev/shm") else None
    if not base:
        import tempfile
        base = tempfile.gettempdir()
    return os.path.join(base, "ldt-shared-cache.bin")


_TIER = None
_TIER_BUILT = False


def shared_tier():
    """Process-wide singleton view of the shared table, lazily built
    from the knobs on first use — the sync batcher's cache and the aio
    front's cache must write through ONE mmap, not two. Built during
    single-threaded service init; tests reset via reset_shared_tier."""
    global _TIER, _TIER_BUILT
    if not _TIER_BUILT:
        _TIER = build_from_env()
        _TIER_BUILT = True
    return _TIER


def reset_shared_tier() -> None:
    global _TIER, _TIER_BUILT
    if _TIER is not None:
        _TIER.close()
    _TIER, _TIER_BUILT = None, False


def build_from_env():
    """The process's shared tier per LDT_RESULT_CACHE_SHM_MB, or None
    when the knob is unset/0. Never raises: a failed attach logs and
    runs private-cache-only."""
    mb = knobs.get_float("LDT_RESULT_CACHE_SHM_MB") or 0.0
    if mb <= 0:
        return None
    path = default_path()
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        cache = SharedResultCache(path, int(mb * 1024 * 1024))
    except Exception as e:  # noqa: BLE001 - degraded, not down
        import json
        print(json.dumps({"msg": "shared result cache unavailable — "
                                 "running with private caches only",
                          "path": path, "error": repr(e)}),
              flush=True)
        return None
    import json
    print(json.dumps({"msg": "shared result cache attached",
                      "path": path, "slots": cache.slot_count,
                      "mb": mb}), flush=True)
    # pre-touch so a scrape shows the series at 0 before any traffic
    for name in ("ldt_shared_cache_hits_total",
                 "ldt_shared_cache_misses_total",
                 "ldt_shared_cache_evictions_total",
                 "ldt_shared_cache_epoch_flush_total"):
        telemetry.REGISTRY.counter_inc(name, 0)
    return cache
