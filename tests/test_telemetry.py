"""Telemetry layer (round 7): histogram bucket math, span-tree
nesting/grafting, compile-event tracking, the slow-request sampler, a
strict exposition-format lint of the full /metrics body, and the
end-to-end acceptance check — a request served through the sync front
produces a span tree covering parse -> dedup -> pack -> dispatch ->
encode whose span sum lands within 20% of the measured latency.
"""
from __future__ import annotations

import json
import threading
import urllib.request

import pytest

from language_detector_tpu import telemetry
from language_detector_tpu.telemetry import (BUCKET_EDGES_MS, Histogram,
                                             SlowTraceRing, Trace)


def _require_engine():
    from language_detector_tpu import native
    if not native.available():
        pytest.skip("native packer unavailable")
    from language_detector_tpu.models.ngram import NgramBatchEngine
    return NgramBatchEngine


# -- Histogram ---------------------------------------------------------------


def test_histogram_bucket_math():
    h = Histogram()
    # bucket edges are 0.05 * 2^k: 0.05, 0.1, 0.2, 0.4, ...
    h.observe(0.05)   # == edge 0 -> bucket 0 (le is inclusive)
    h.observe(0.06)   # -> bucket 1 (le 0.1)
    h.observe(0.3)    # -> bucket 3 (le 0.4)
    h.observe(1e9)    # -> +Inf overflow bucket
    counts, total_sum, count, vmax = h.snapshot()
    assert count == 4
    assert total_sum == pytest.approx(0.05 + 0.06 + 0.3 + 1e9)
    assert vmax == 1e9
    assert counts[0] == 1 and counts[1] == 1 and counts[3] == 1
    assert counts[len(BUCKET_EDGES_MS)] == 1  # overflow slot
    assert sum(counts) == 4


def test_histogram_percentiles():
    h = Histogram()
    for v in (1.0, 2.0, 3.0, 4.0, 100.0):
        h.observe(v)
    p50 = h.percentile(50)
    assert 0.8 <= p50 <= 3.2  # inside the holding bucket's range
    assert h.percentile(100) == pytest.approx(100.0)
    assert Histogram().percentile(50) is None


def test_histogram_thread_safety():
    h = Histogram()
    n = 5000

    def worker():
        for _ in range(n):
            h.observe(1.0)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    _, total_sum, count, _ = h.snapshot()
    assert count == 4 * n
    assert total_sum == pytest.approx(4 * n * 1.0)


# -- Trace spans -------------------------------------------------------------


def test_trace_nesting_and_ordering():
    tr = Trace()
    base = tr.t0
    # request-level spans at depth 0; engine spans grafted at depth 1
    tr.add("parse", base, base + 0.001)
    tr.add("detect", base + 0.001, base + 0.010)
    flush = Trace()
    flush.add("dedup", base + 0.002, base + 0.003)
    flush.add("pack", base + 0.003, base + 0.005)
    flush.add("dispatch", base + 0.005, base + 0.009)
    tr.graft(flush, depth=1)
    tr.add("encode", base + 0.010, base + 0.011)
    d = tr.to_dict(total_ms=11.0, meta={"front": "test"})
    names = [s["name"] for s in d["spans"]]
    # sorted by start time: children interleave inside their parent
    assert names == ["parse", "detect", "dedup", "pack", "dispatch",
                     "encode"]
    depths = {s["name"]: s["depth"] for s in d["spans"]}
    assert depths["parse"] == depths["detect"] == depths["encode"] == 0
    assert depths["dedup"] == depths["pack"] == depths["dispatch"] == 1
    assert d["total_ms"] == 11.0
    assert d["meta"] == {"front": "test"}
    # durations survive the render
    by = {s["name"]: s for s in d["spans"]}
    assert by["detect"]["dur_ms"] == pytest.approx(9.0, abs=0.01)
    assert tr.span_ms("pack") == pytest.approx(2.0, abs=0.01)


def test_observe_stage_returns_end_and_records():
    telemetry.REGISTRY.reset()
    tr = Trace()
    t1 = telemetry.observe_stage("unit_stage", tr.t0, tr.t0 + 0.004,
                                 trace=tr)
    assert t1 == tr.t0 + 0.004
    h = telemetry.REGISTRY.histogram("ldt_stage_latency_ms",
                                     stage="unit_stage")
    _, total_sum, count, _ = h.snapshot()
    assert count == 1 and total_sum == pytest.approx(4.0)
    assert tr.spans[0][0] == "unit_stage"


# -- slow-request sampler ----------------------------------------------------


def test_slow_ring_threshold_and_eviction():
    ring = SlowTraceRing(capacity=3, threshold_ms=10.0)
    fast = Trace()
    assert not ring.maybe_record(fast, 5.0)
    assert ring.snapshot() == []
    for i in range(5):
        tr = Trace()
        tr.add("detect", tr.t0, tr.t0 + 0.02)
        assert ring.maybe_record(tr, 20.0 + i, meta={"i": i})
    held = ring.snapshot()
    assert len(held) == 3                     # ring bound
    assert ring.recorded == 5                 # evictions still counted
    assert [t["meta"]["i"] for t in held] == [2, 3, 4]  # newest win
    ring.clear()
    assert ring.snapshot() == [] and ring.recorded == 0


def test_slow_ring_off_by_default():
    ring = SlowTraceRing(capacity=4, threshold_ms=0.0)
    tr = Trace()
    assert not ring.maybe_record(tr, 1e9)     # sampler disabled


def test_error_trace_captured_despite_threshold():
    """Regression for the error-capture gap: a 5xx answer keeps its
    span tree (tagged reason:error) even when the sampler is off /
    far above the request's latency — and a fast 2xx still records
    nothing."""
    slow = telemetry.REGISTRY.slow
    old_thresh = slow.threshold_ms
    slow.clear()
    slow.threshold_ms = 0.0                  # sampler fully off
    try:
        ok = Trace()
        ok.request_id = "fine-1"
        telemetry.finish_request(ok, meta={"front": "sync",
                                           "status": 200})
        assert slow.snapshot() == []
        before = telemetry.REGISTRY.counter_value(
            "ldt_error_traces_total")
        err = Trace()
        err.request_id = "boom-1"
        err.add("detect", err.t0, err.t0 + 0.001)
        telemetry.finish_request(err, meta={"front": "sync",
                                            "status": 500})
        held = slow.snapshot()
        assert len(held) == 1
        assert held[0]["meta"]["reason"] == "error"
        assert held[0]["meta"]["status"] == 500
        assert held[0]["request_id"] == "boom-1"
        assert [s["name"] for s in held[0]["spans"]] == ["detect"]
        assert telemetry.REGISTRY.counter_value(
            "ldt_error_traces_total") == before + 1
    finally:
        slow.threshold_ms = old_thresh
        slow.clear()


# -- compile-event tracking --------------------------------------------------


def test_compile_counter_two_shapes():
    """First execution of a new padded wire shape increments
    ldt_xla_compiles_total{lane=...} exactly once; re-dispatching the
    same shape does not."""
    NgramBatchEngine = _require_engine()
    import bench
    telemetry.REGISTRY.reset()
    eng = NgramBatchEngine()
    short = bench.make_corpus(96)
    eng.detect_batch(short)
    lane_counts = telemetry.REGISTRY.compile_counts()
    first = sum(lane_counts.values())
    assert first >= 1
    # same corpus -> same padded shapes -> no new compiles
    eng.detect_batch(short)
    assert sum(telemetry.REGISTRY.compile_counts().values()) == first
    # much longer documents -> different padded chunk geometry -> at
    # least one fresh shape per affected lane, counted exactly once
    long_docs = [" ".join(bench.make_corpus(40)) + f" tail{i}"
                 for i in range(96)]
    eng.detect_batch(long_docs)
    second = sum(telemetry.REGISTRY.compile_counts().values())
    assert second > first
    eng.detect_batch(long_docs)
    assert sum(telemetry.REGISTRY.compile_counts().values()) == second
    # compile wall-time histogram observed once per compile event
    fams = dict((f[0], f) for f in telemetry.REGISTRY.families())
    assert "ldt_xla_compile_ms" in fams
    count_samples = [v for name, _, v in fams["ldt_xla_compile_ms"][3]
                     if name.endswith("_count")]
    assert sum(count_samples) == second


# -- exposition rendering ----------------------------------------------------


def _lint_exposition(body: str):
    """Strict parse of a Prometheus text-format body: every sample
    belongs to a HELP+TYPE'd family declared exactly once, label values
    are well-formed, histogram buckets are cumulative and le="+Inf"
    equals _count."""
    import re
    declared: dict = {}
    samples: list = []
    help_seen: set = set()
    for line in body.strip("\n").split("\n"):
        assert line == line.rstrip(), f"trailing whitespace: {line!r}"
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert name not in help_seen, f"duplicate HELP {name}"
            help_seen.add(name)
            continue
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split()
            assert name not in declared, f"duplicate TYPE {name}"
            assert mtype in ("counter", "gauge", "histogram", "summary")
            assert name in help_seen, f"TYPE {name} before HELP"
            declared[name] = mtype
            continue
        assert not line.startswith("#"), f"unknown comment: {line!r}"
        m = re.fullmatch(
            r'([a-zA-Z_:][a-zA-Z0-9_:]*)'
            r'(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*='
            r'"(?:[^"\\\n]|\\\\|\\"|\\n)*",?)*)\})?'
            r' (NaN|[-+]?(?:\d+\.?\d*(?:e[-+]?\d+)?|\.\d+|Inf))',
            line)
        assert m, f"malformed sample line: {line!r}"
        series, labels, value = m.group(1), m.group(2), m.group(3)
        family = series
        for suffix in ("_bucket", "_sum", "_count"):
            base = series[:-len(suffix)] if series.endswith(suffix) \
                else None
            if base and declared.get(base) == "histogram":
                family = base
        assert family in declared, f"sample without TYPE: {line!r}"
        samples.append((series, labels or "", float(value)
                        if value not in ("NaN", "Inf") else value))
    # histogram internal consistency
    for name, mtype in declared.items():
        if mtype != "histogram":
            continue
        buckets = [(lb, v) for s, lb, v in samples
                   if s == f"{name}_bucket"]
        assert buckets, f"histogram {name} has no buckets"
        counts = {lb: v for s, lb, v in samples
                  if s == f"{name}_count"}
        # group by the labels minus le
        groups: dict = {}
        for lb, v in buckets:
            le = re.search(r'le="([^"]*)"', lb).group(1)
            rest = re.sub(r',?le="[^"]*"', "", lb).strip(",")
            groups.setdefault(rest, []).append((le, v))
        for rest, bs in groups.items():
            vals = [v for _, v in bs]
            assert vals == sorted(vals), \
                f"{name}{{{rest}}} buckets not cumulative"
            inf = [v for le, v in bs if le == "+Inf"]
            assert len(inf) == 1, f"{name}{{{rest}}} missing le=+Inf"
            total = next(v for lb, v in counts.items()
                         if lb.strip(",") == rest)
            assert inf[0] == total, \
                f"{name}{{{rest}}} +Inf {inf[0]} != _count {total}"
    return declared, samples


def test_metrics_exposition_lint():
    from language_detector_tpu.service.server import Metrics
    telemetry.REGISTRY.reset()
    m = Metrics()
    m.inc("augmentation_requests_total")
    m.inc_object("successful", 3)
    # label values that need escaping
    m.add_languages({'W"eird\\Lang\nName': 2, "English": 5})
    m.observe_request_ms(12.5)
    telemetry.REGISTRY.histogram("ldt_stage_latency_ms",
                                 stage="pack").observe(1.25)
    telemetry.REGISTRY.counter_inc("ldt_xla_compiles_total", lane="main")
    body = m.render()
    declared, samples = _lint_exposition(body)
    assert declared["ldt_request_latency_ms"] == "histogram"
    assert declared["ldt_stage_latency_ms"] == "histogram"
    assert declared["ldt_xla_compiles_total"] == "counter"
    # legacy series still emitted, derived from the histogram sum
    assert declared["augmentation_request_duration_milliseconds"] == \
        "counter"
    legacy = [v for s, _, v in samples
              if s == "augmentation_request_duration_milliseconds"]
    assert legacy == [12.5]
    # escaped label value round-trips
    assert 'language="W\\"eird\\\\Lang\\nName"' in body


# -- /debug/vars + acceptance through the sync front -------------------------


@pytest.fixture(scope="module")
def traced_server():
    """Sync front over the DEVICE engine (CPU backend): the acceptance
    criterion needs the real scheduler's dedup/pack/dispatch spans."""
    _require_engine()
    from language_detector_tpu.service.server import (DetectorService,
                                                      make_server)
    telemetry.REGISTRY.reset()
    # sample every request so the tests can read full span trees back
    telemetry.REGISTRY.slow.threshold_ms = 0.0001
    svc = DetectorService(use_device=True, max_delay_ms=1.0)
    if svc._engine is None:
        pytest.skip("device engine unavailable")
    httpd, metricsd, svc = make_server(0, 0, service=svc)
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in (httpd, metricsd)]
    for t in threads:
        t.start()
    yield {"url": f"http://127.0.0.1:{httpd.server_address[1]}",
           "metrics_url":
               f"http://127.0.0.1:{metricsd.server_address[1]}",
           "svc": svc}
    httpd.shutdown()
    metricsd.shutdown()
    svc.batcher.close()
    telemetry.REGISTRY.reset()


def _post_docs(url, texts):
    body = json.dumps(
        {"request": [{"text": t} for t in texts]}).encode()
    req = urllib.request.Request(
        url + "/", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        return resp.status, json.loads(resp.read())


def _get_json(url):
    with urllib.request.urlopen(url, timeout=30) as resp:
        return json.loads(resp.read())


def test_request_span_tree_acceptance(traced_server):
    """A request through the sync front yields a span tree covering
    parse -> dedup -> pack -> dispatch -> encode whose depth-0 span sum
    is within 20% of the recorded end-to-end latency."""
    import bench
    # > TINY_BATCH_C_PATH distinct docs so the flush takes the real
    # pack/dispatch path rather than the all-C tiny shortcut
    texts = bench.make_corpus(200)
    telemetry.REGISTRY.slow.clear()
    status, doc = _post_docs(traced_server["url"], texts)
    assert status in (200, 203)
    assert len(doc["response"]) == 200
    held = telemetry.REGISTRY.slow.snapshot()
    assert held, "slow sampler (threshold ~0) captured nothing"
    tr = held[-1]
    names = {s["name"] for s in tr["spans"]}
    for required in ("parse", "dedup", "pack", "dispatch", "encode"):
        assert required in names, f"span {required} missing: {names}"
    # handler spans at depth 0, engine flush spans grafted deeper
    depth = {s["name"]: s["depth"] for s in tr["spans"]}
    assert depth["parse"] == depth["detect"] == depth["encode"] == 0
    assert depth["dedup"] >= 1 and depth["pack"] >= 1
    # depth-0 spans tile the request: their sum must explain the
    # measured end-to-end latency to within 20%
    top_ms = sum(s["dur_ms"] for s in tr["spans"] if s["depth"] == 0)
    assert top_ms == pytest.approx(tr["total_ms"], rel=0.20), \
        (top_ms, tr["total_ms"])


def test_metrics_endpoint_lint_and_series(traced_server):
    import bench
    _post_docs(traced_server["url"], bench.make_corpus(100))
    with urllib.request.urlopen(traced_server["metrics_url"] + "/",
                                timeout=30) as resp:
        body = resp.read().decode()
    declared, samples = _lint_exposition(body)
    by_series = {}
    for s, lb, v in samples:
        by_series.setdefault(s, []).append(v)
    assert sum(by_series["ldt_request_latency_ms_count"]) > 0
    assert sum(by_series["ldt_stage_latency_ms_count"]) > 0
    assert sum(by_series.get("ldt_xla_compiles_total", [0])) > 0


def test_debug_vars_endpoint(traced_server):
    d = _get_json(traced_server["metrics_url"] + "/debug/vars")
    assert d["pid"] > 0 and d["uptime_sec"] >= 0
    assert d["rss_bytes"] > 0
    assert d["requests"]["count"] > 0
    assert "engine" in d and "counters" in d
    assert d["counters"]["augmentation_requests_total"] > 0
    assert isinstance(d["stage_latency_ms"], dict)
    assert "dispatch" in d["stage_latency_ms"]
    for stats in d["stage_latency_ms"].values():
        assert set(stats) == {"count", "mean", "p50", "p95", "p99"}


def test_debug_slow_endpoint_and_cli(traced_server, tmp_path, capsys):
    d = _get_json(traced_server["metrics_url"] + "/debug/slow")
    assert d["threshold_ms"] == telemetry.REGISTRY.slow.threshold_ms
    assert d["recorded"] >= 1
    assert d["traces"], "every request samples at threshold ~0"
    # the CLI pretty-printer consumes the same JSON (file source)
    src = tmp_path / "slow.json"
    src.write_text(json.dumps(d))
    from language_detector_tpu.debug import _main
    assert _main(["--slow-traces", str(src)]) == 0
    out = capsys.readouterr().out
    assert "slow traces:" in out
    assert "parse" in out and "dispatch" in out


def test_debug_vars_shared_serializer_aio():
    """Both fronts serve the SAME debug_vars serializer — the aio
    metrics handler routes /debug/vars and /debug/slow too."""
    import asyncio

    from language_detector_tpu.service.aioserver import serve
    from language_detector_tpu.service.server import DetectorService

    async def run():
        svc = DetectorService(use_device=False, start_batcher=False)
        loop = asyncio.get_running_loop()
        ready = loop.create_future()
        task = loop.create_task(serve(0, 0, svc=svc, ready=ready))
        port, mport = await asyncio.wait_for(ready, timeout=30)

        def fetch(path):
            return json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{mport}{path}", timeout=10).read())

        dv = await loop.run_in_executor(None, fetch, "/debug/vars")
        slow = await loop.run_in_executor(None, fetch, "/debug/slow")
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass
        return dv, slow

    dv, slow = asyncio.run(run())
    assert dv["pid"] > 0 and "requests" in dv
    assert set(slow) == {"threshold_ms", "capacity", "recorded",
                         "traces"}
