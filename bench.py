"""End-to-end throughput benchmark: prints ONE JSON line.

Measures the batched detection pipeline (host pack -> device score -> host
epilogue) in docs/sec on the available accelerator, and the stage split for
diagnosis. vs_baseline is measured throughput / per-chip target, where the
target is the BASELINE.json north star (1M docs/sec on v5e-8 = 125K
docs/sec/chip at ~200-byte service documents).
"""
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

PER_CHIP_TARGET = 1_000_000 / 8  # docs/sec (BASELINE.json north star, v5e-8)

# Budget for one full `python -m tools.lint` run (all analyzers, whole
# tree, including the bounded model checker and the torn-write crash
# schedules). ci.sh runs the suite on every pass, so --smoke measures
# it and fails when it stops being cheap; the live run is ~4s, so 30s
# absorbs a loaded CI host without hiding a real regression (an
# accidental state-space or crash-schedule blowup lands well past
# this).
LINT_BUDGET_MS = 30_000

# Per-record budgets for the always-on observability hot paths: one
# flight-recorder emit (JSON encode + mmap store) and one trace span.
# Both are single-digit microseconds in practice; 50µs absorbs a
# loaded CI host while still catching an accidental fsync, lock
# convoy, or O(n) scan creeping into the per-request path.
TELEM_BUDGET_NS = 50_000

# Integrity scrub overhead ceiling: one full scrub+canary cycle
# (ops/kernels.table_digest fold + the 8-doc golden pack, per lane)
# amortized over LDT_SCRUB_INTERVAL_SEC must stay under 1% of serving
# capacity — corruption detection rides the data plane for free.
SCRUB_BUDGET_FRAC = 0.01

# Self-contained corpus: service-sized snippets in several scripts; padded
# with index salt so quad repeat filters see realistic variety.
_SEEDS = [
    "The quick brown fox jumps over the lazy dog near the river bank today",
    "Le gouvernement a annoncé de nouvelles mesures pour aider les familles",
    "Der Hund läuft schnell durch den großen Wald und findet einen Knochen",
    "El rápido zorro marrón salta sobre el perro perezoso cerca del río",
    "Быстрая коричневая лиса прыгает через ленивую собаку сегодня утром",
    "こんにちは世界。今日はとても良い天気ですね。散歩に行きましょう。",
    "Η γρήγορη καφέ αλεπού πηδά πάνω από το τεμπέλικο σκυλί σήμερα",
    "De snelle bruine vos springt over de luie hond bij de rivier vandaag",
    "Il veloce volpe marrone salta sopra il cane pigro vicino al fiume",
    "A rápida raposa marrom pula sobre o cachorro preguiçoso perto do rio",
]


def make_corpus(n: int) -> list:
    """n service-like documents (~150-250 bytes) cycling scripts; word
    order varies deterministically so the squeeze/repeat predictors see
    natural text, not synthetic repetition."""
    import random
    rng = random.Random(42)
    vocab = [s.split() for s in _SEEDS]
    out = []
    for i in range(n):
        words = list(vocab[i % len(_SEEDS)])
        rng.shuffle(words)
        k = 18 + (i * 7) % 14
        out.append(" ".join((words * 3)[:k]))
    return out


def make_mixed_corpus(n: int) -> list:
    """Realistic traffic mix: service-sized docs plus a spam tail (1%
    squeeze-trigger documents), 2% long documents (3-8KB), and 1%
    degenerate inputs. Measures what the clean corpus cannot: squeeze,
    retry, and long-doc cost."""
    docs = make_corpus(n)
    for i in range(0, n, 100):            # 1% spam -> squeeze fallback
        docs[i] = ("buy cheap now " * 300).strip()
    for i in range(37, n, 50):            # 2% long docs
        parts = [docs[(i + j * 13 + 1) % n] for j in range(20 + i % 21)]
        docs[i] = " ".join(parts)
    for i in range(73, n, 100):           # 1% degenerate
        docs[i] = ["", "   ", "123 !!!", "a"][i // 100 % 4]
    return docs


def make_longheavy_corpus(n: int) -> list:
    """Long-document-heavy mix: 25% of documents are 3-20KB (multi-span,
    multi-chunk), the rest service-sized — a second composition keeping
    the chunk-major design honest (per-document cost must stay linear
    when long docs dominate the byte volume). Report MB/s alongside
    docs/sec: the average document here is ~10x the service mix."""
    docs = make_corpus(n)
    base = list(docs)  # compose from the pristine service docs only
    for i in range(0, n, 4):              # 25% long docs, 3-20KB
        reps = 20 + (i * 7) % 120
        parts = [base[(i + j * 11 + 3) % n] for j in range(reps)]
        docs[i] = " ".join(parts)
    return docs


def bench(batch_size: int = 16384, n_batches: int = 6,
          http_bench: bool = True) -> dict:
    from language_detector_tpu.models.ngram import NgramBatchEngine

    # HTTP service path (asyncio front, in-process load) runs FIRST, in
    # a subprocess, while this process has not yet touched the device —
    # two live clients contend on the tunneled chip and would halve the
    # measured number (so --smoke and --profile, whose parent already
    # holds the device, skip it). Best effort: a hung or failed service
    # bench must never sink the engine bench.
    http_docs_sec = None
    http_cold_docs_sec = None
    http_detail: dict = {}
    if http_bench:
        import subprocess

        def _service_bench(args, timeout, env=None):
            r = subprocess.run(
                [sys.executable,
                 str(REPO / "tools" / "bench_service.py"), *args],
                capture_output=True, text=True, timeout=timeout,
                env=env)
            for line in reversed(r.stdout.splitlines()):
                if line.startswith("{"):
                    d = json.loads(line)
                    if d["detail"]["errors"] == 0 and \
                            d["detail"]["total_docs"] > 0:
                        return d
                    break
            return None

        try:
            d = _service_bench(["--aio", "98304", "16", "2048"], 300)
            if d is not None:
                http_docs_sec = d["value"]
                det = d["detail"]
                http_detail = dict(
                    http_parse_ms=det.get("parse_ms_mean"),
                    http_parse_ms_p95=det.get("parse_ms_p95"),
                    http_serialize_ms=det.get("serialize_ms_mean"),
                    http_serialize_ms_p95=det.get("serialize_ms_p95"),
                    http_parse_fast_hit_rate=det.get(
                        "parse_fast_hit_rate"),
                    uds_docs_sec=det.get("uds_docs_sec"),
                )
        except Exception:  # noqa: BLE001 - informational metric only
            pass
        # honest cold: a FRESH worker process with a FRESH (empty)
        # persistent compile-cache dir, so the pass actually pays the
        # compiles instead of inheriting the warm pass's jit state (the
        # old in-process "cold" pass read ABOVE warm whenever the
        # persistent cache was already hot — BENCH_r06's 5241 vs 4896)
        try:
            import os as _os
            import tempfile as _tf
            with _tf.TemporaryDirectory(prefix="ldt-coldcache-") as td:
                env = dict(_os.environ, LDT_COMPILE_CACHE_DIR=td)
                d = _service_bench(
                    ["--aio-cold", "98304", "16", "2048"], 600, env=env)
                if d is not None:
                    http_cold_docs_sec = d["value"]
        except Exception:  # noqa: BLE001 - informational metric only
            pass
        if http_docs_sec and http_cold_docs_sec:
            http_detail["http_cold_warm_ratio"] = round(
                http_cold_docs_sec / http_docs_sec, 3)

    eng = NgramBatchEngine()
    docs = make_corpus(batch_size)
    # DISTINCT docs across the whole stream: the engine's batch-internal
    # dedup is always on, and a stream of n_batches repeated blocks
    # would collapse to one block's work — inflating the headline ~6x
    # and breaking cross-round comparability (make_corpus docs share
    # the same length/script distribution either way, so the scoring
    # work per doc matches earlier rounds)
    stream = make_corpus(batch_size * n_batches)
    total_bytes = sum(len(d.encode()) for d in stream)

    # Warm-up: compile + device transfer paths
    eng.detect_batch(docs[:batch_size])

    # Sustained pipelined throughput (pack N+1 overlaps device-score N).
    # Headline = best of 7 runs: the shared host fluctuates +-25% with
    # multi-second lumps, and the best run is the least-interfered
    # measurement of the pipeline itself (NOT sustained throughput); the
    # median is reported alongside so cross-round comparisons stay
    # honest (7 samples keep a couple of stalled runs from sinking it).
    p0 = eng.pipeline_stats()
    runs = []
    for _ in range(7):
        t0 = time.time()
        results = eng.detect_many(stream, batch_size=batch_size)
        runs.append((time.time() - t0) / n_batches)
    t_e2e = min(runs)
    t_e2e_med = sorted(runs)[len(runs) // 2]
    # pack/score overlap over the sustained multi-slice runs only
    # (delta, so the single-slice warm-up's unoverlapped pack does not
    # dilute the ratio): the fraction of host pack time spent while a
    # device dispatch was in flight. Depth 1 pins this to 0.0.
    p1 = eng.pipeline_stats()
    d_pack = p1["pack_ms_total"] - p0["pack_ms_total"]
    d_over = p1["pack_ms_overlapped"] - p0["pack_ms_overlapped"]
    pack_overlap_ratio = (d_over / d_pack) if d_pack > 0 else 0.0

    # Codes-only path: the reference's production semantic (wrapper.cc
    # returns just the code string; the service/eval layers consume this)
    cruns = []
    for _ in range(2):
        t0 = time.time()
        eng.detect_codes(stream, batch_size=batch_size)
        cruns.append((time.time() - t0) / n_batches)
    t_codes = min(cruns)

    # Stage split (one batch, serial, informational). pack_ms includes
    # the wire layout (the flat pack's begin+finish phases).
    from language_detector_tpu import native
    t0 = time.time()
    cb = native.pack_chunks_native(docs, eng.tables, eng.reg,
                                   flags=eng.flags)
    t_pack = time.time() - t0
    n_fallback = int(cb.fallback.sum())
    t0 = time.time()
    import numpy as np
    from language_detector_tpu.ops.score import unpack_chunks_out
    rows = unpack_chunks_out(np.asarray(eng._score_fn(eng.dt, cb.wire)),
                             cb.wire["cmeta"])
    t_score = time.time() - t0
    t0 = time.time()
    native.epilogue_flat_native(rows, cb, eng.flags, eng.reg)
    t_epi = time.time() - t0

    # Mixed-traffic run (spam/long/degenerate tail): reported in detail so
    # the headline stays comparable across rounds while the realistic mix
    # is measured rather than assumed. Per-run times land in the detail
    # so a stalled run is visible as host interference rather than
    # read as engine variance.
    mixed = make_mixed_corpus(batch_size)
    eng.detect_many(mixed, batch_size=batch_size)  # warm retry/long shapes
    for k in ("fallback_docs", "scalar_recursion_docs", "dedup_docs",
              "retry_lane_dispatches", "retry_offtier_docs"):
        eng.stats[k] = 0
    for k in list(eng.stats):
        if k.startswith("tier_"):
            eng.stats[k] = 0
    mruns = []
    for _ in range(5):
        t0 = time.time()
        eng.detect_many(mixed, batch_size=batch_size)
        mruns.append(time.time() - t0)
    t_mixed = min(mruns)
    mixed_docs_sec = batch_size / t_mixed
    mixed_docs_sec_med = batch_size / sorted(mruns)[len(mruns) // 2]
    mixed_fallback = eng.stats["fallback_docs"] // 5
    mixed_retried = eng.stats["scalar_recursion_docs"] // 5  # per pass
    mixed_dedup = eng.stats["dedup_docs"] // 5
    mixed_retry_lane = eng.stats["retry_lane_dispatches"] // 5
    # tier-keyed retry bins (PR 9): a retried doc re-enters at its own
    # bucket tier, so off-tier retries are structurally zero — reported
    # (and asserted by ci.sh) so the inflation cannot silently return
    mixed_retry_offtier = eng.stats["retry_offtier_docs"]
    tier_dispatches = {
        k[len("tier_"):-len("_dispatches")]: v // 5
        for k, v in sorted(eng.stats.items()) if k.startswith("tier_")}

    # Result-cache pass (service/batcher.py bounded LRU): the mixed
    # corpus submitted twice through a cache-enabled batcher — the
    # second pass is ~all hits, measuring what repeated hot documents
    # cost once cached. The service exports the same hit rate as
    # ldt_result_cache_hit_rate.
    from language_detector_tpu.service.batcher import Batcher
    cache_hit_rate = None
    cached_docs_sec = None
    cbat = Batcher(lambda ts: eng.detect_codes(ts, batch_size=batch_size),
                   cache_bytes=64 << 20)
    try:
        cbat.submit(mixed).result(timeout=600)  # fill pass
        t0 = time.time()
        cbat.submit(mixed).result(timeout=600)  # hit pass
        t_cached = time.time() - t0
        cs = cbat.cache_stats()
        cache_hit_rate = round(cs["hit_rate"], 4)
        cached_docs_sec = round(batch_size / t_cached, 1)
    finally:
        cbat.close()

    # Second mix: long-doc-heavy (25% of docs 3-20KB; ~10x the bytes of
    # the service mix per doc, so MB/s is the honest scale here)
    lh_n = max(batch_size // 4, 1024)
    longheavy = make_longheavy_corpus(lh_n)
    lh_bytes = sum(len(d.encode()) for d in longheavy)
    eng.stats["longdoc_split_docs"] = 0
    eng.detect_many(longheavy, batch_size=batch_size)  # warm shapes
    lruns = []
    for _ in range(3):
        t0 = time.time()
        eng.detect_many(longheavy, batch_size=batch_size)
        lruns.append(time.time() - t0)
    t_lh = min(lruns)
    t_lh_med = sorted(lruns)[len(lruns) // 2]
    lh_split_docs = eng.stats["longdoc_split_docs"] // 4  # per pass
    # before/after for the span-parallel lane: the same corpus through
    # an engine with the lane OFF (oversize docs resolve scalar, the
    # pre-PR-9 behavior), so the speedup is measured, not assumed
    eng_nc = NgramBatchEngine(longdoc_chunk_slots=0)
    eng_nc.detect_many(longheavy, batch_size=batch_size)  # warm shapes
    ncruns = []
    for _ in range(3):
        t0 = time.time()
        eng_nc.detect_many(longheavy, batch_size=batch_size)
        ncruns.append(time.time() - t0)
    t_lh_nc = min(ncruns)

    # Fault-injection guard cost (docs/ROBUSTNESS.md): with LDT_FAULTS
    # unset every seam is one module-attribute load + identity test.
    # Measure it so the zero-overhead claim stays a number the CI can
    # watch, not a promise in the docs.
    from language_detector_tpu import faults
    guard_n = 1_000_000
    t0 = time.time()
    for _ in range(guard_n):
        if faults.ACTIVE is not None:
            faults.evaluate("device_flush")
    fault_guard_ns = (time.time() - t0) / guard_n * 1e9

    # Per-stage latency percentiles from the shared telemetry registry:
    # every engine run above observed dedup/tier_plan/pack/dispatch/
    # epilogue/retry_lane stage histograms, so the bench reports WHERE
    # the time went, not just end-to-end wall time.
    from language_detector_tpu import telemetry
    stage_latency = telemetry.REGISTRY.stage_percentiles()
    xla_compiles = telemetry.REGISTRY.compile_counts()

    docs_sec = len(stream) / (t_e2e * n_batches)
    return dict(
        metric="batch_detect_throughput",
        value=round(docs_sec, 1),
        unit="docs/sec",
        vs_baseline=round(docs_sec / PER_CHIP_TARGET, 4),
        detail=dict(
            batch_size=batch_size,
            n_batches=n_batches,
            doc_bytes_avg=round(total_bytes / len(stream), 1),
            mb_sec=round(total_bytes / (t_e2e * n_batches) / 1e6, 2),
            pack_ms=round(t_pack * 1e3, 1),
            score_ms=round(t_score * 1e3, 1),
            epilogue_ms=round(t_epi * 1e3, 1),
            e2e_ms_per_batch=round(t_e2e * 1e3, 1),
            docs_sec_median=round(len(docs) / t_e2e_med, 1),
            codes_docs_sec=round(len(docs) / t_codes, 1),
            fallback_docs=n_fallback,
            mixed_docs_sec=round(mixed_docs_sec, 1),
            mixed_docs_sec_median=round(mixed_docs_sec_med, 1),
            mixed_run_ms=[round(r * 1e3) for r in mruns],
            mixed_fallback_docs=int(mixed_fallback),
            mixed_retried_docs=int(mixed_retried),
            mixed_dedup_docs=int(mixed_dedup),
            mixed_retry_lane_dispatches=int(mixed_retry_lane),
            tier_dispatches=tier_dispatches,
            cache_hit_rate=cache_hit_rate,
            cached_docs_sec=cached_docs_sec,
            longheavy_docs_sec=round(lh_n / t_lh, 1),
            longheavy_docs_sec_median=round(lh_n / t_lh_med, 1),
            longheavy_mb_sec=round(lh_bytes / t_lh / 1e6, 2),
            longheavy_doc_bytes_avg=round(lh_bytes / lh_n, 1),
            longheavy_docs_sec_nochunk=round(lh_n / t_lh_nc, 1),
            longheavy_mb_sec_nochunk=round(
                lh_bytes / t_lh_nc / 1e6, 2),
            longheavy_lane_speedup=round(t_lh_nc / t_lh, 3),
            longheavy_split_docs=int(lh_split_docs),
            mixed_retry_offtier_docs=int(mixed_retry_offtier),
            pack_overlap_ratio=round(pack_overlap_ratio, 4),
            pipeline_depth=int(p1["depth"]),
            kernel=p1["kernel"],
            kernel_reason=p1["kernel_reason"],
            pipeline_donation_hits=int(
                p1["donation_hits"] - p0["donation_hits"]),
            http_docs_sec=http_docs_sec,
            http_cold_docs_sec=http_cold_docs_sec,
            http_engine_ratio=round(http_docs_sec / docs_sec, 3)
            if http_docs_sec else None,
            **http_detail,
            faults_disabled=faults.ACTIVE is None,
            fault_guard_ns=round(fault_guard_ns, 1),
            stage_latency_ms=stage_latency,
            xla_compiles=xla_compiles,
            summary_sample=results[0].summary_lang,
        ),
    )




def bench_kernel(n: int = 4096, reps: int = 10) -> dict:
    """--kernel: the scoring-kernel A/B (ops/kernels.py) over real
    packed wires, one bucket tier per corpus composition. Times the
    device dispatch alone (block_until_ready fenced, reps averaged) for
    each mode — the reference XLA program, the quantized fused program,
    and the lax.scan oracle — plus the Pallas kernel where the backend
    lowers it (interpret mode is timed on a tiny wire only, it is a
    parity tool, not a serving mode). Engine-level docs/sec under the
    two serving candidates and a scalar-engine sample anchor the
    dispatch numbers to end-to-end throughput.

    vs_baseline carries the acceptance ratio: fused-vs-xla dispatch
    speedup on the service tier (the round-14 floor is 1.3x)."""
    import numpy as np

    from language_detector_tpu.models.ngram import NgramBatchEngine
    from language_detector_tpu.ops import kernels
    from language_detector_tpu.ops.score import score_chunks

    eng = NgramBatchEngine()
    sel = kernels.select_kernel()
    modes = {
        "xla": score_chunks,
        "fused": kernels.score_chunks_fused,
        "lax": kernels.score_chunks_lax,
    }
    if sel.mode == "pallas":          # TPU: time the real kernel too
        modes["pallas"] = sel.score

    corpora = [
        ("service", make_corpus(n)),
        ("mixed", make_mixed_corpus(n)),
        ("longheavy", make_longheavy_corpus(max(n // 4, 1024))),
    ]
    tiers = {}
    for tier, docs in corpora:
        # copy out of the staging ring: the next tier's pack reuses the
        # ring slots, and the A/B must time identical bytes
        cb = eng._pack(docs)
        wire = {k: np.array(v, copy=True) for k, v in cb.wire.items()}
        G = int(np.prod(wire["cmeta"].shape))
        K = int(wire["k_iota"].shape[0])
        per = {}
        for name, fn in modes.items():
            fn(eng.dt, wire).block_until_ready()   # compile + warm
            t0 = time.perf_counter()
            for _ in range(reps):
                out = fn(eng.dt, wire)
            out.block_until_ready()
            per[name] = (time.perf_counter() - t0) / reps * 1e3
        tiers[tier] = dict(
            grid_g=G, grid_k=K, n_docs=len(docs),
            dispatch_ms={k: round(v, 2) for k, v in per.items()},
            fused_vs_xla=round(per["xla"] / per["fused"], 3),
            lax_vs_xla=round(per["xla"] / per["lax"], 3),
        )

    # Pallas interpret: one tiny dispatch, presence + parity cost only
    # (the interpreter runs the kernel body in Python per grid tile)
    pallas_interpret_ms = None
    if kernels._HAVE_PALLAS and sel.mode != "pallas":
        small = eng._pack(make_corpus(64))
        wire = {k: np.array(v, copy=True) for k, v in small.wire.items()}
        ps, _, _ = kernels._pallas_score_fns(interpret=True)
        ps(eng.dt, wire).block_until_ready()
        t0 = time.perf_counter()
        ps(eng.dt, wire).block_until_ready()
        pallas_interpret_ms = round((time.perf_counter() - t0) * 1e3, 1)

    # engine-level docs/sec under the two serving candidates (same
    # corpus, same engine config, only LDT_KERNEL differs) + scalar
    import os

    from language_detector_tpu.engine_scalar import detect_scalar
    docs = make_corpus(n)
    engine_docs_sec = {}
    saved = os.environ.get("LDT_KERNEL")
    try:
        for mode in ("xla", "fused" if sel.mode != "pallas"
                     else "pallas"):
            os.environ["LDT_KERNEL"] = mode
            e = NgramBatchEngine()
            e.detect_batch(docs)                   # warm shapes
            t0 = time.time()
            e.detect_batch(docs)
            engine_docs_sec[e.pipeline_stats()["kernel"]] = round(
                n / (time.time() - t0), 1)
    finally:
        if saved is None:
            os.environ.pop("LDT_KERNEL", None)
        else:
            os.environ["LDT_KERNEL"] = saved
    t0 = time.time()
    for t in docs[:256]:
        detect_scalar(t, eng.tables, eng.reg)
    scalar_docs_sec = round(256 / (time.time() - t0), 1)

    ratio = tiers["service"]["fused_vs_xla"]
    return dict(
        metric="kernel_dispatch_speedup",
        value=ratio,
        unit="x (fused vs xla, service tier)",
        vs_baseline=round(ratio / 1.3, 4),    # round-14 acceptance floor
        detail=dict(
            backend=__import__("jax").default_backend(),
            kernel_selected=sel.mode,
            kernel_reason=sel.reason,
            tiers=tiers,
            pallas_interpret_ms_small=pallas_interpret_ms,
            engine_docs_sec=engine_docs_sec,
            scalar_docs_sec=scalar_docs_sec,
            reps=reps,
        ),
    )


def make_longtail_corpus(n: int) -> list:
    """Fat-tail documents (~18-60KB) past the default
    LDT_LONGDOC_SPLIT_SLOTS engage threshold, so every one takes the
    span-split lane. Each doc is dominated by one script with a sprinkle
    of foreign sentences (quoted text, the realistic long-article shape):
    multi-span enough to split, single-language enough to pass the
    reliability gate — which is the population the lane exists for
    (gate-failing docs re-score whole regardless)."""
    import random
    rng = random.Random(7)
    out = []
    for i in range(n):
        home = _SEEDS[i % len(_SEEDS)]
        foreign = _SEEDS[(i + 3) % len(_SEEDS)]
        words = home.split()
        target = 18_000 + (i * 4099) % 42_000
        parts, size = [], 0
        while size < target:
            rng.shuffle(words)
            sent = " ".join(words)
            if rng.random() < 0.08:       # ~8% embedded foreign spans
                sent = foreign
            parts.append(sent)
            size += len(sent) + 1
        out.append(" ".join(parts))
    return out


def bench_longdoc(n: int = 256) -> dict:
    """--longdoc: the span-parallel lane in isolation over a fat-tail
    corpus. A/B against the lane off (oversize docs resolve scalar, the
    pre-PR-9 behavior) plus an exactness spot-check vs the scalar
    engine, so the lane's speedup AND its identity claim are measured
    in one place."""
    from language_detector_tpu.engine_scalar import detect_scalar
    from language_detector_tpu.models.ngram import NgramBatchEngine

    corpus = make_longtail_corpus(n)
    total_bytes = sum(len(d.encode()) for d in corpus)

    eng = NgramBatchEngine()
    eng.detect_many(corpus[:16], batch_size=4096)  # warm shapes
    eng.stats["longdoc_split_docs"] = 0
    eng.stats["longdoc_subdocs"] = 0
    p0 = eng.pipeline_stats()
    runs = []
    for _ in range(3):
        t0 = time.time()
        results = eng.detect_many(corpus, batch_size=4096)
        runs.append(time.time() - t0)
    t_lane = min(runs)
    p1 = eng.pipeline_stats()

    eng_nc = NgramBatchEngine(longdoc_chunk_slots=0)
    eng_nc.detect_many(corpus[:16], batch_size=4096)  # warm shapes
    ncruns = []
    for _ in range(3):
        t0 = time.time()
        eng_nc.detect_many(corpus, batch_size=4096)
        ncruns.append(time.time() - t0)
    t_nc = min(ncruns)

    # exactness spot-check: lane output must be byte-identical to the
    # scalar engine (the full 100+-doc sweep lives in test_pipeline)
    mismatches = 0
    for t, r in zip(corpus[:8], results[:8]):
        want = detect_scalar(t, eng.tables, eng.reg)
        if (r.summary_lang, tuple(r.language3)) != (
                want.summary_lang, tuple(want.language3)):
            mismatches += 1

    mb_sec = total_bytes / t_lane / 1e6
    return dict(
        metric="longdoc_lane_throughput",
        value=round(mb_sec, 2),
        unit="MB/sec",
        vs_baseline=round(t_nc / t_lane, 4),
        detail=dict(
            n_docs=n,
            doc_bytes_avg=round(total_bytes / n, 1),
            lane_mb_sec=round(mb_sec, 2),
            nochunk_mb_sec=round(total_bytes / t_nc / 1e6, 2),
            lane_speedup=round(t_nc / t_lane, 3),
            lane_run_ms=[round(r * 1e3) for r in runs],
            nochunk_run_ms=[round(r * 1e3) for r in ncruns],
            split_docs=int(eng.stats["longdoc_split_docs"] // 3),
            subdocs=int(eng.stats["longdoc_subdocs"] // 3),
            longdoc_chunks=int(
                p1["longdoc_chunks"] - p0["longdoc_chunks"]),
            scalar_mismatches=mismatches,
        ),
    )


def bench_multichip_child(n_devices: int) -> dict:
    """Pooled multi-lane throughput over an n-device mesh (runs inside
    the re-exec'd child: JAX_PLATFORMS/XLA_FLAGS/LDT_POOL_LANES are
    already set). Lanes partition the mesh into sub-meshes; concurrent
    submitters (one per lane) drive the pool the way the batcher's
    widened flush workers do in the service."""
    import threading

    import jax

    from language_detector_tpu.models.ngram import NgramBatchEngine
    from language_detector_tpu.parallel.mesh import batch_mesh

    mesh = batch_mesh(n_devices)
    eng = NgramBatchEngine(mesh=mesh)
    if eng.pool is None:
        raise RuntimeError("pool off — LDT_POOL_LANES not handed down")
    n_lanes = len(eng.pool.lanes)

    batch = 4096
    n_rounds = 3
    # one distinct stream per submitter per round: the engine's
    # batch-internal dedup would collapse repeated blocks
    corpus = make_corpus(batch * n_lanes * n_rounds)
    streams = [corpus[i * batch * n_rounds:(i + 1) * batch * n_rounds]
               for i in range(n_lanes)]

    # warm every lane's program: round-robin rotation covers the pool
    for _ in range(n_lanes):
        eng.detect_codes(corpus[:batch], batch_size=batch)

    def run_once() -> float:
        errors: list = []

        def body(stream):
            try:
                eng.detect_codes(stream, batch_size=batch)
            except BaseException as e:  # noqa: BLE001 - join surfaces it
                errors.append(e)

        ts = [threading.Thread(target=body, args=(s,)) for s in streams]
        t0 = time.time()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if errors:
            raise errors[0]
        return len(corpus) / (time.time() - t0)

    runs = sorted(run_once() for _ in range(3))
    docs_sec = runs[-1]
    stats = eng.pool.stats()
    return dict(
        metric="multichip_pool_throughput",
        value=round(docs_sec, 1),
        unit="docs/sec",
        vs_baseline=round(docs_sec / (PER_CHIP_TARGET * n_devices), 4),
        detail=dict(
            n_devices=n_devices,
            n_lanes=n_lanes,
            lane_mesh_size=stats["lane_mesh_size"],
            lanes_active=stats["lanes_active"],
            batch_size=batch,
            rounds=n_rounds,
            docs_total=len(corpus),
            docs_sec_median=round(runs[len(runs) // 2], 1),
            docs_sec_runs=[round(r, 1) for r in runs],
            per_lane_dispatches={str(ln["lane"]): ln["dispatches"]
                                 for ln in stats["lanes"]},
            per_lane_ewma_ms={str(ln["lane"]): round(ln["ewma_ms"], 1)
                              for ln in stats["lanes"]},
            simulated=jax.devices()[0].platform == "cpu",
        ),
    )


def run_multichip(n_devices: int) -> dict:
    """Re-exec bench_multichip_child with an n-device virtual mesh and
    the pool on (env must land before jax first imports), then write
    MULTICHIP_r06.json at the repo root."""
    import os
    import subprocess
    env = os.environ.copy()
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") +
        f" --xla_force_host_platform_device_count={n_devices}")
    env["LDT_POOL_LANES"] = str(max(2, n_devices // 2))
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py"),
         "--multichip-child", str(n_devices)],
        cwd=str(REPO), env=env, capture_output=True, text=True,
        timeout=900)
    for line in reversed(r.stdout.splitlines()):
        if line.startswith("{"):
            out = json.loads(line)
            break
    else:
        raise RuntimeError(
            f"multichip child produced no result (rc={r.returncode}): "
            f"{r.stderr[-2000:]}")
    with open(REPO / "MULTICHIP_r06.json", "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    return out


def bench_fleet(n_workers: int = 3, total_docs: int = 24576,
                clients: int = 32, docs_per_request: int = 256) -> dict:
    """Fleet saturation section: aggregate docs/sec and request p99
    through an N-worker REUSEPORT fleet (service/fleet.py), against an
    LDT_FLEET_WORKERS=1 baseline on the same host. Zero-drop is an
    ASSERTION, not a statistic: any non-2xx status or connection-level
    failure during the timed pass fails the bench — admission bounds
    stay unset, so the fleet has no legitimate shed path here."""
    import http.client
    import os
    import signal
    import socket
    import subprocess
    import threading
    import urllib.request

    docs = make_corpus(total_docs)
    payloads = []
    for r in range(total_docs // docs_per_request):
        chunk = docs[r * docs_per_request:(r + 1) * docs_per_request]
        payloads.append(json.dumps(
            {"request": [{"text": d} for d in chunk]}).encode())

    def _free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def _pass(workers: int) -> dict:
        port, sport = _free_port(), _free_port()
        env = os.environ.copy()
        env.update({
            "LISTEN_PORT": str(port),
            # liveness-only members: the bench drives saturation itself,
            # it does not need the queue-depth health plane
            "PROMETHEUS_PORT": "0",
            "LDT_FLEET_WORKERS": str(workers),
            "LDT_FLEET_STATUS_PORT": str(sport),
        })
        log = open(f"/tmp/ldt_fleet_bench_{workers}.log", "w")
        sup = subprocess.Popen(
            [sys.executable, "-m",
             "language_detector_tpu.service.supervisor",
             "language_detector_tpu.service.aioserver"],
            env=env, stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True)
        try:
            deadline = time.time() + 300
            while True:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{sport}/fleetz",
                            timeout=5) as resp:
                        if json.loads(resp.read().decode())["ready"] \
                                == workers:
                            break
                except Exception:  # noqa: BLE001 - still booting
                    pass
                if sup.poll() is not None:
                    raise RuntimeError(f"fleet died rc={sup.poll()}")
                if time.time() > deadline:
                    raise RuntimeError(f"{workers}-worker fleet never "
                                       "became ready")
                time.sleep(0.2)

            lock = threading.Lock()
            drops = [0]

            def drive(work, lat, count):
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=120)
                while True:
                    with lock:
                        if not work:
                            break
                        payload = work.pop()
                    t0 = time.time()
                    try:
                        conn.request(
                            "POST", "/", payload,
                            {"Content-Type": "application/json"})
                        resp = conn.getresponse()
                        body = resp.read()
                    except Exception:  # noqa: BLE001 - counted as drop
                        with lock:
                            drops[0] += 1
                        conn.close()
                        conn = http.client.HTTPConnection(
                            "127.0.0.1", port, timeout=120)
                        continue
                    if resp.status in (200, 203):
                        n = body.count(b'"iso6391code"')
                        ms = (time.time() - t0) * 1e3
                        with lock:
                            count[0] += n
                            if lat is not None:
                                lat.append(ms)
                    else:
                        with lock:
                            drops[0] += 1
                conn.close()

            def run_pass(lat, count):
                work = list(payloads)
                threads = [threading.Thread(target=drive,
                                            args=(work, lat, count))
                           for _ in range(clients)]
                t0 = time.time()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                return time.time() - t0

            # untimed warm pass: REUSEPORT spreads the connections, so
            # every member pays its bucket-ladder compiles here
            run_pass(None, [0])
            drops[0] = 0
            lat: list = []
            count = [0]
            took = run_pass(lat, count)
            assert drops[0] == 0, \
                f"{drops[0]} dropped requests in the timed pass " \
                f"({workers} workers) — the fleet bench must be zero-drop"
            assert count[0] > 0, "nothing served in the timed pass"

            sup.send_signal(signal.SIGINT)
            rc = sup.wait(timeout=120)
            assert rc == 0, f"fleet exit {rc}"
            lat.sort()
            return dict(
                docs_sec=round(count[0] / took, 1),
                total_docs=count[0],
                took_sec=round(took, 2),
                p50_ms=round(lat[len(lat) // 2], 1),
                p99_ms=round(lat[min(len(lat) - 1,
                                     int(len(lat) * 0.99))], 1),
                drops=drops[0],
            )
        finally:
            try:
                os.killpg(sup.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            sup.wait(timeout=30)
            log.close()

    base = _pass(1)
    fleet = _pass(n_workers)
    host_cores = os.cpu_count() or 1
    detail = dict(
        fleet_workers=n_workers,
        clients=clients,
        docs_per_request=docs_per_request,
        host_cores=host_cores,
        zero_drop=True,
        fleet_speedup=round(fleet["docs_sec"] / base["docs_sec"], 3),
        **fleet,
        **{"baseline_" + k: v for k, v in base.items()},
    )
    if host_cores < n_workers:
        # same rule as the multichip section: N workers time-sharing
        # fewer than N cores cannot show the real scaling — the numbers
        # are honest for THIS host, the ratio is what transfers
        detail["scaling_caveat"] = (
            f"host has {host_cores} core(s) for {n_workers} workers: "
            "members time-share the CPU, so aggregate throughput "
            "cannot exceed one worker's — compare ratios only; the "
            ">=2x claim requires >= fleet_workers cores")
    return dict(
        metric="service_fleet_saturation",
        value=fleet["docs_sec"],
        unit="docs/sec",
        detail=detail,
    )


def bench_shm(total_docs: int = 8192, docs_per_request: int = 64) -> dict:
    """Shared-memory ring lane (service/shmring.py) vs the framed UDS
    lane, both served by ONE sync-front worker so the scorer is the
    shared bottleneck and only the transport differs (the sync front
    scores both lanes on the caller's thread — no event-loop bridge to
    muddy the comparison). The UDS pass is the lane's natural shape
    (one framed request in flight per connection); the shm pass
    pipelines across the ring's slots, which is the whole point of the
    lane. Three gates, all ASSERTIONS:
      - zero-drop: every doc of both timed passes answers 2xx,
      - shm_docs_sec >= uds_docs_sec (the lane must pay for itself),
      - a hard p99 ceiling on the shm pass — a stuck lease, a fence
        hang, or a sweep stall shows up as a blown tail long before it
        shows up as a timeout, so the bench doubles as a liveness gate.
    """
    import os
    import signal
    import socket
    import struct
    import subprocess
    import tempfile
    import urllib.request

    from language_detector_tpu.service import shmring

    docs = make_corpus(total_docs)
    payloads = []
    for r in range(total_docs // docs_per_request):
        chunk = docs[r * docs_per_request:(r + 1) * docs_per_request]
        payloads.append(json.dumps(
            {"request": [{"text": d} for d in chunk]}).encode())

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    tmp = tempfile.mkdtemp(prefix="ldt_shm_bench_")
    uds_path = os.path.join(tmp, "ldt.sock")
    shm_dir = os.path.join(tmp, "rings")
    env = os.environ.copy()
    env.update({
        "LISTEN_PORT": str(port),
        "PROMETHEUS_PORT": "0",
        "LDT_UNIX_SOCKET": uds_path,
        "LDT_SHM_DIR": shm_dir,
    })
    log = open("/tmp/ldt_shm_bench.log", "w")
    srv = subprocess.Popen(
        [sys.executable, "-m", "language_detector_tpu.service.server"],
        cwd=str(REPO), env=env, stdout=log, stderr=subprocess.STDOUT,
        start_new_session=True)
    hdr = struct.Struct("!I")
    rhdr = struct.Struct("!IH")
    try:
        deadline = time.time() + 300
        while True:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/readyz",
                        timeout=5) as resp:
                    if resp.status == 200 and os.path.exists(uds_path):
                        break
            except Exception:  # noqa: BLE001 - still booting
                pass
            if srv.poll() is not None:
                raise RuntimeError(f"worker died rc={srv.poll()}")
            if time.time() > deadline:
                raise RuntimeError("worker never became ready")
            time.sleep(0.2)

        def uds_pass(lat, drops):
            conn = socket.socket(socket.AF_UNIX)
            conn.connect(uds_path)
            served = 0
            t0 = time.time()
            for body in payloads:
                t1 = time.time()
                conn.sendall(hdr.pack(len(body)) + body)
                raw = b""
                while len(raw) < rhdr.size:
                    chunk = conn.recv(rhdr.size - len(raw))
                    if not chunk:
                        raise RuntimeError("UDS peer closed mid-frame")
                    raw += chunk
                length, status = rhdr.unpack(raw)
                resp = bytearray()
                while len(resp) < length:
                    chunk = conn.recv(length - len(resp))
                    if not chunk:
                        raise RuntimeError("UDS peer closed mid-body")
                    resp += chunk
                if status in (200, 203):
                    served += bytes(resp).count(b'"iso6391code"')
                    if lat is not None:
                        lat.append((time.time() - t1) * 1e3)
                else:
                    drops[0] += 1
            conn.close()
            return served, time.time() - t0

        def shm_pass(cli, lat, drops):
            served = 0
            pending = []          # (slot, t_submit), submit order
            t0 = time.time()

            def drain_oldest():
                nonlocal served
                i, t1 = pending.pop(0)
                status, resp = cli.wait(i, timeout=60.0)
                if status in (200, 203):
                    served += resp.count(b'"iso6391code"')
                    if lat is not None:
                        lat.append((time.time() - t1) * 1e3)
                else:
                    drops[0] += 1

            for body in payloads:
                while True:
                    i = cli.submit(body)
                    if i is not None:
                        break
                    drain_oldest()      # ring full: free a slot first
                pending.append((i, time.time()))
            while pending:
                drain_oldest()
            return served, time.time() - t0

        # untimed warm passes: both lanes pay the bucket-ladder
        # compiles before anything is measured
        warm_drops = [0]
        uds_pass(None, warm_drops)
        cli = shmring.RingClient(shm_dir)
        cli.wait_attached(60.0)
        shm_pass(cli, None, warm_drops)

        # two timed passes per lane, interleaved, best-of per lane:
        # a single-core host gives ±1% run-to-run scheduling noise,
        # larger than the lane difference under test. Every pass —
        # kept or not — must still be zero-drop and fully served.
        lanes = {}
        for _ in range(2):
            for name, one_pass in (
                    ("uds", uds_pass),
                    ("shm", lambda l, d: shm_pass(cli, l, d))):
                lat: list = []
                drops = [0]
                served, took = one_pass(lat, drops)
                assert drops[0] == 0, \
                    f"{drops[0]} dropped frames on the {name} lane — " \
                    "the shm bench must be zero-drop"
                assert served == total_docs, \
                    f"{name} lane answered {served}/{total_docs} docs"
                lat.sort()
                res = dict(
                    docs_sec=round(served / took, 1),
                    took_sec=round(took, 2),
                    p50_ms=round(lat[len(lat) // 2], 2),
                    p99_ms=round(lat[min(len(lat) - 1,
                                         int(len(lat) * 0.99))], 2),
                    drops=0,
                )
                if name not in lanes or \
                        res["docs_sec"] > lanes[name]["docs_sec"]:
                    lanes[name] = res
        cli.close(unlink=True)

        p99_ceiling_ms = 5_000.0
        assert lanes["shm"]["p99_ms"] < p99_ceiling_ms, \
            f"shm p99 {lanes['shm']['p99_ms']}ms blew the " \
            f"{p99_ceiling_ms}ms ceiling — a lease/fence stall, " \
            "not a throughput problem"
        assert lanes["shm"]["docs_sec"] >= lanes["uds"]["docs_sec"], \
            f"shm lane ({lanes['shm']['docs_sec']} docs/s) slower " \
            f"than UDS ({lanes['uds']['docs_sec']} docs/s)"

        srv.send_signal(signal.SIGTERM)
        rc = srv.wait(timeout=120)
        assert rc == 0, f"worker exit {rc}"
        return dict(
            metric="shm_ring_ingest",
            value=lanes["shm"]["docs_sec"],
            unit="docs/sec",
            detail=dict(
                total_docs=total_docs,
                docs_per_request=docs_per_request,
                zero_drop=True,
                p99_ceiling_ms=p99_ceiling_ms,
                shm_over_uds=round(lanes["shm"]["docs_sec"] /
                                   lanes["uds"]["docs_sec"], 3),
                **{"shm_" + k: v for k, v in lanes["shm"].items()},
                **{"uds_" + k: v for k, v in lanes["uds"].items()},
            ),
        )
    finally:
        try:
            os.killpg(srv.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        srv.wait(timeout=30)
        log.close()


_COLDSTART_CHILD = """\
import json, sys, time
t0 = time.perf_counter()
from language_detector_tpu.models.ngram import NgramBatchEngine
t1 = time.perf_counter()
docs = json.load(open(sys.argv[1]))
eng = NgramBatchEngine()
codes = eng.detect_codes(docs, batch_size=4096)
t2 = time.perf_counter()
st = eng._aot.stats() if getattr(eng, "_aot", None) is not None else None
json.dump({"import_ms": round((t1 - t0) * 1e3, 1),
           "cold_to_ready_ms": round((t2 - t1) * 1e3, 1),
           "dispatches": eng.stats["device_dispatches"],
           "aot": st, "codes": codes},
          open(sys.argv[2], "w"))
"""


def bench_coldstart(fleet_workers: int = 2, unique_docs: int = 256,
                    requests: int = 256) -> dict:
    """--coldstart: the round-16 boot-hot A/B (BENCH_r11.json).

    Part 1 — cold-to-ready ladder, one fresh subprocess per mode:
    engine construction + first full detect over a service corpus with
    (a) nothing cached, (b) a warm persistent compile cache
    (LDT_COMPILE_CACHE_DIR), (c) the warm compile cache plus an AOT
    executable bundle (LDT_AOT_DIR). The AOT leg must load, not
    compile, and all three modes must answer bit-identically.

    Part 2 — duplicate-heavy fleet pass: a REUSEPORT fleet with the
    shm result tier armed serves a corpus where every member sees the
    same documents, against a private-cache fleet on the same corpus.
    A member's own fills live in its L1 and never reach the shm probe,
    so the shared-cache hit counters scraped from each member's
    /debug/vars count *cross-process* reuse by construction.
    """
    import http.client
    import shutil
    import signal
    import socket
    import subprocess
    import tempfile
    import threading
    import urllib.request

    work = tempfile.mkdtemp(prefix="ldt-coldstart-")
    docs = make_corpus(unique_docs)
    docs_file = os.path.join(work, "docs.json")
    with open(docs_file, "w") as f:
        json.dump(docs, f)
    cc_dir = os.path.join(work, "compile-cache")
    aot_dir = os.path.join(work, "aot-bundle")

    def run_child(tag: str, env_extra: dict) -> dict:
        out = os.path.join(work, f"{tag}.json")
        env = os.environ.copy()
        env.pop("LDT_COMPILE_CACHE_DIR", None)
        env.pop("LDT_AOT_DIR", None)
        env.update(env_extra)
        r = subprocess.run(
            [sys.executable, "-c", _COLDSTART_CHILD, docs_file, out],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=900)
        assert r.returncode == 0, f"{tag} child: {r.stderr[-4000:]}"
        with open(out) as f:
            return json.load(f)

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def fleet_pass(shm_mb: float) -> dict:
        port, sport, mbase = free_port(), free_port(), free_port()
        env = os.environ.copy()
        env.update({
            "LISTEN_PORT": str(port),
            "PROMETHEUS_PORT": str(mbase),
            "LDT_FLEET_WORKERS": str(fleet_workers),
            "LDT_FLEET_STATUS_PORT": str(sport),
            # boot-hot members: the part-1 prep child warmed both
            "LDT_COMPILE_CACHE_DIR": cc_dir,
            "LDT_AOT_DIR": aot_dir,
            # the shm tier rides the per-worker cache — L1 must be on
            "LDT_RESULT_CACHE_MB": "64",
        })
        env.pop("LDT_RESULT_CACHE_SHM_MB", None)
        if shm_mb:
            env["LDT_RESULT_CACHE_SHM_MB"] = str(shm_mb)
        log = open(os.path.join(work, f"fleet-{shm_mb}.log"), "w")
        sup = subprocess.Popen(
            [sys.executable, "-m",
             "language_detector_tpu.service.supervisor",
             "language_detector_tpu.service.aioserver"],
            env=env, stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True)

        def fleetz():
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{sport}/fleetz",
                    timeout=5) as resp:
                return json.loads(resp.read().decode())

        try:
            deadline = time.time() + 300
            while True:
                try:
                    if fleetz()["ready"] == fleet_workers:
                        break
                except Exception:  # noqa: BLE001 - still booting
                    pass
                if sup.poll() is not None:
                    raise RuntimeError(f"fleet died rc={sup.poll()}")
                if time.time() > deadline:
                    raise RuntimeError("fleet never became ready")
                time.sleep(0.2)

            # duplicate-heavy: every request carries the SAME corpus,
            # so whichever member answers first publishes and the rest
            # can only reuse across the process boundary
            payload = json.dumps(
                {"request": [{"text": d} for d in docs]}).encode()
            lock = threading.Lock()
            state = {"left": requests, "docs": 0, "drops": 0}

            def drive():
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=120)
                while True:
                    with lock:
                        if state["left"] <= 0:
                            break
                        state["left"] -= 1
                    try:
                        conn.request(
                            "POST", "/", payload,
                            {"Content-Type": "application/json"})
                        resp = conn.getresponse()
                        body = resp.read()
                    except Exception:  # noqa: BLE001 - counted as drop
                        with lock:
                            state["drops"] += 1
                        conn.close()
                        conn = http.client.HTTPConnection(
                            "127.0.0.1", port, timeout=120)
                        continue
                    with lock:
                        if resp.status in (200, 203):
                            state["docs"] += body.count(
                                b'"iso6391code"')
                        else:
                            state["drops"] += 1
                conn.close()

            def run_pass(n: int) -> float:
                with lock:
                    state["left"] = n
                threads = [threading.Thread(target=drive)
                           for _ in range(8)]
                t0 = time.time()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                return time.time() - t0

            # warm pass, SEQUENTIAL with a fresh connection each time:
            # REUSEPORT hops connections across members, so the first
            # member to serve publishes into the shm tier and the
            # others take their first exposure as cross-process hits.
            # (A concurrent warm would race every member through its
            # private miss path in the same instant and the L1s would
            # absorb all the duplicates before the tier is ever probed
            # again — first exposure is exactly what the tier exists
            # for, so it is what the bench serializes.)
            for _ in range(4 * fleet_workers):
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=120)
                conn.request("POST", "/", payload,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                assert resp.status in (200, 203), resp.status
                conn.close()
            with lock:
                state["docs"] = 0
                state["drops"] = 0
            took = run_pass(requests)
            assert state["drops"] == 0, \
                f"{state['drops']} drops — the pass must be zero-drop"
            assert state["docs"] > 0, "nothing served in the timed pass"

            shared = []
            for m in fleetz()["members"]:
                mp = int(m.get("metrics_port") or 0)
                if mp <= 0:
                    continue
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{mp}/debug/vars",
                        timeout=10) as resp:
                    dv = json.loads(resp.read().decode())
                sc = dv.get("shared_cache")
                if sc:
                    shared.append({"slot": m["slot"],
                                   "hits": sc["hits"],
                                   "misses": sc["misses"],
                                   "hit_rate": sc["hit_rate"]})
            sup.send_signal(signal.SIGINT)
            rc = sup.wait(timeout=120)
            assert rc == 0, f"fleet exit {rc}"
            return {"docs_sec": round(state["docs"] / took, 1),
                    "total_docs": state["docs"],
                    "took_sec": round(took, 2),
                    "members_with_shared_stats": shared}
        finally:
            try:
                os.killpg(sup.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            sup.wait(timeout=30)
            log.close()

    try:
        prep = run_child("prep", {"LDT_COMPILE_CACHE_DIR": cc_dir,
                                  "LDT_AOT_DIR": aot_dir})
        assert prep["dispatches"] > 0, "corpus never dispatched"
        assert prep["aot"]["exports"] > 0, prep["aot"]
        no_cache = run_child("no_cache", {})
        compile_cache = run_child("compile_cache",
                                  {"LDT_COMPILE_CACHE_DIR": cc_dir})
        aot = run_child("aot", {"LDT_COMPILE_CACHE_DIR": cc_dir,
                                "LDT_AOT_DIR": aot_dir})
        assert aot["aot"]["loads"] > 0, aot["aot"]
        assert aot["aot"]["refusals"] == 0, aot["aot"]
        assert aot["codes"] == compile_cache["codes"] == \
            no_cache["codes"], \
            "cold-start modes must answer bit-identically"

        shared_fleet = fleet_pass(8.0)
        cross_hits = sum(m["hits"] for m in
                         shared_fleet["members_with_shared_stats"])
        assert cross_hits > 0, \
            "duplicate-heavy pass produced no cross-member hits: " \
            + json.dumps(shared_fleet)
        private_fleet = fleet_pass(0.0)
        assert not private_fleet["members_with_shared_stats"], \
            "private baseline must not attach a shared tier"

        ratio = aot["cold_to_ready_ms"] \
            / max(compile_cache["cold_to_ready_ms"], 1e-9)
        result = {
            "bench": "coldstart",
            "unique_docs": unique_docs,
            "fleet_workers": fleet_workers,
            "no_cache": {k: no_cache[k] for k in
                         ("import_ms", "cold_to_ready_ms")},
            "compile_cache": {k: compile_cache[k] for k in
                              ("import_ms", "cold_to_ready_ms")},
            "aot": {"import_ms": aot["import_ms"],
                    "cold_to_ready_ms": aot["cold_to_ready_ms"],
                    "loads": aot["aot"]["loads"]},
            "aot_vs_compile_cache": round(ratio, 3),
            "bit_identical": True,
            "duplicate_heavy_fleet": {
                "shared": shared_fleet,
                "private_baseline": private_fleet,
                "cross_member_hits": cross_hits,
                "shared_vs_private": round(
                    shared_fleet["docs_sec"]
                    / max(private_fleet["docs_sec"], 1e-9), 3),
            },
        }
        # the 0.5x gate from the round-16 acceptance list — loud here,
        # held again (cheaper) by the ci.sh boot-hot smoke
        assert ratio <= 0.5, \
            f"AOT cold-to-ready {aot['cold_to_ready_ms']}ms is " \
            f"{ratio:.2f}x the compile-cache path — gate is 0.5x"
        return result
    finally:
        shutil.rmtree(work, ignore_errors=True)


def bench_telemetry_overhead(n: int = 20_000) -> dict:
    """ns per flight-recorder event and per trace span, measured on
    the real code paths (armed recorder into a temp ring, module-level
    emit_event; Trace spans via observe_stage)."""
    import shutil
    import tempfile

    from language_detector_tpu import flightrec, telemetry

    tmp = tempfile.mkdtemp(prefix="ldt-bench-fr-")
    saved = flightrec.RECORDER
    try:
        flightrec.RECORDER = flightrec.FlightRecorder(
            flightrec.ring_path(tmp), slots=256, slot_bytes=512)
        t0 = time.perf_counter()
        for i in range(n):
            flightrec.emit_event("request_end", request_id="bench",
                                 status=200, total_ms=1.25)
        event_ns = (time.perf_counter() - t0) * 1e9 / n
        flightrec.RECORDER.close()
    finally:
        flightrec.RECORDER = saved
        shutil.rmtree(tmp, ignore_errors=True)
    spans_per_trace = 8
    t0 = time.perf_counter()
    for i in range(n // spans_per_trace):
        tr = telemetry.Trace()
        t = tr.t0
        for _ in range(spans_per_trace):
            t = telemetry.observe_stage("bench", t, trace=tr)
    span_ns = (time.perf_counter() - t0) * 1e9 \
        / ((n // spans_per_trace) * spans_per_trace)
    # the calibration loops above are not workload: drop their stage
    # histograms so the real bench summary stays clean
    telemetry.REGISTRY.reset()
    return {"flightrec_ns_per_event": round(event_ns, 1),
            "trace_ns_per_span": round(span_ns, 1)}


def _synth_replay_text(tenant_hash: int, seq: int, target_bytes: int,
                       dup_modulo: int = 16) -> str:
    """Deterministic payload synthesis for replay: the capture stores
    shape (size bucket, doc count), never content, so replay fabricates
    text to the recorded size. Keying the RNG on (tenant, seq %
    dup_modulo) makes each tenant cycle a small set of distinct
    documents — the duplicate-heavy stream that exercises the result
    caches the way real tenant traffic does."""
    import random
    rng = random.Random((tenant_hash & 0xFFFFFFFF) * 31
                        + seq % dup_modulo)
    vocab = _SEEDS[rng.randrange(len(_SEEDS))].split()
    words = []
    size = 0
    while size < max(target_bytes, 8):
        w = vocab[rng.randrange(len(vocab))]
        words.append(w)
        size += len(w.encode()) + 1
    return " ".join(words)


def replay_records(records: list, port: int, speedup: float = 1.0,
                   clients: int = 8) -> dict:
    """Re-drive a merged capture against a live front on 127.0.0.1:
    each record becomes one POST with synthesized docs to the recorded
    size bucket, the recorded tenant/priority/deadline headers, fired
    on the recorded arrival schedule compressed by `speedup`. Returns
    schedule fidelity (achieved-vs-recorded send-time skew) and
    per-tenant latency/shed/error SLIs."""
    import http.client
    import threading

    if not records:
        return {"requests": 0, "error": "empty capture"}
    speedup = max(float(speedup), 1e-6)
    arr0 = records[0]["arrival_ns"]
    plan = []
    for i, r in enumerate(records):
        offset = (r["arrival_ns"] - arr0) / 1e9 / speedup
        docs_n = max(int(r.get("docs", 1)), 1)
        target = max(int(r.get("approx_bytes", 256)), 64)
        texts = [_synth_replay_text(r.get("tenant_hash", 0), i * 131 + j,
                                    max(target // docs_n, 8))
                 for j in range(docs_n)]
        body = json.dumps(
            {"request": [{"text": t} for t in texts]}).encode()
        headers = {"Content-Type": "application/json",
                   "X-LDT-Tenant": r.get("tenant", "default")}
        if r.get("priority"):
            headers["X-LDT-Priority"] = "1"
        if r.get("deadline_ms"):
            headers["X-LDT-Deadline-Ms"] = str(int(r["deadline_ms"]))
        plan.append((offset, r.get("tenant", "default"), body, headers,
                     docs_n))

    lock = threading.Lock()
    cursor = [0]
    sent: list = []            # (scheduled_offset, actual_offset)
    by_tenant: dict = {}
    counts = {"ok": 0, "shed": 0, "error": 0, "drop": 0}
    ok_lat: list = []          # latency of successful answers only
    ok_docs = [0]              # docs actually served ok (cost proxy)
    t_start = time.time() + 0.5   # shared epoch: lead time to spin up

    def drive():
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=120)
        while True:
            with lock:
                i = cursor[0]
                if i >= len(plan):
                    break
                cursor[0] = i + 1
            offset, tenant, body, headers, docs_n = plan[i]
            delay = t_start + offset - time.time()
            if delay > 0:
                time.sleep(delay)
            actual = time.time() - t_start
            t0 = time.time()
            try:
                conn.request("POST", "/", body, headers)
                resp = conn.getresponse()
                resp.read()
                status = resp.status
            except Exception:  # noqa: BLE001 - counted, not fatal
                with lock:
                    counts["drop"] += 1
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=120)
                continue
            ms = (time.time() - t0) * 1e3
            with lock:
                sent.append((offset, actual))
                t = by_tenant.setdefault(
                    tenant, {"lat": [], "shed": 0, "errors": 0})
                t["lat"].append(ms)
                if status in (429, 503):
                    counts["shed"] += 1
                    t["shed"] += 1
                elif status >= 500:
                    counts["error"] += 1
                    t["errors"] += 1
                else:
                    counts["ok"] += 1
                    ok_lat.append(ms)
                    ok_docs[0] += docs_n
        conn.close()

    threads = [threading.Thread(target=drive)
               for _ in range(min(clients, len(plan)))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = max(time.time() - t_start, 1e-9)

    skews = sorted(abs(a - s) for s, a in sent)
    span_sched = plan[-1][0] if len(plan) > 1 else 0.0

    def _pct(xs, q):
        return xs[min(len(xs) - 1, int(len(xs) * q))] if xs else 0.0

    tenants = {}
    for tenant, d in sorted(by_tenant.items()):
        lat = sorted(d["lat"])
        tenants[tenant] = {
            "requests": len(lat),
            "p50_ms": round(_pct(lat, 0.50), 2),
            "p99_ms": round(_pct(lat, 0.99), 2),
            "shed": d["shed"],
            "errors": d["errors"],
        }
    p95_skew = _pct(skews, 0.95)
    oklat = sorted(ok_lat)
    n_resp = max(len(plan), 1)
    return {
        "requests": len(plan),
        "completed": len(sent),
        "speedup": speedup,
        # overall SLIs in the shape autotune.score() consumes: latency
        # of SUCCESSFUL answers (a shed is fast by construction and
        # must not dilute p99), errors+drops against the error budget,
        # and the docs/sec cost proxy over the achieved wall time
        "sli": {
            "p50_ms": round(_pct(oklat, 0.50), 2),
            "p99_ms": round(_pct(oklat, 0.99), 2),
            "err_pct": round(100.0 * (counts["error"] + counts["drop"])
                             / n_resp, 3),
            "shed_pct": round(100.0 * counts["shed"] / n_resp, 3),
            "ok_docs_per_sec": round(ok_docs[0] / wall, 2),
            "wall_sec": round(wall, 3),
        },
        "span_scheduled_sec": round(span_sched, 3),
        "schedule": {
            "p50_skew_ms": round(_pct(skews, 0.50) * 1e3, 2),
            "p95_skew_ms": round(p95_skew * 1e3, 2),
            "max_skew_ms": round((skews[-1] if skews else 0) * 1e3, 2),
            # the acceptance ratio: p95 send-time skew as a fraction
            # of the replayed span (<= 0.10 reproduces the schedule)
            "skew_frac_p95": round(p95_skew / span_sched, 4)
            if span_sched > 0 else 0.0,
        },
        "counts": counts,
        "tenants": tenants,
    }


def synth_capture_records(n: int = 2000, tenants: int = 32,
                          rate_rps: float = 200.0,
                          seed: int = 1234) -> list:
    """Synthetic capture for `--replay-synth zipf`: zipfian tenant skew
    (rank-r tenant gets ~1/r of the traffic) over exponential
    interarrivals, small doc counts, service-sized byte buckets, and a
    10% priority mix — the duplicate-heavy skewed stream that makes
    the PR 16 shared cache earn its keep. Records use the
    merge_captures() dict shape, so the replay driver cannot tell them
    from a real capture."""
    import random

    from language_detector_tpu import capture as cap

    rng = random.Random(seed)
    weights = [1.0 / r for r in range(1, tenants + 1)]
    total_w = sum(weights)
    cum = []
    acc = 0.0
    for w in weights:
        acc += w / total_w
        cum.append(acc)
    out = []
    t_ns = 0
    for i in range(n):
        t_ns += int(rng.expovariate(rate_rps) * 1e9)
        u = rng.random()
        rank = next(r for r, edge in enumerate(cum) if u <= edge)
        tenant = f"tenant-{rank:02d}"
        out.append({
            "arrival_ns": t_ns,
            "tenant": tenant,
            "tenant_hash": cap.tenant_hash(tenant),
            "docs": 1 + rng.randrange(8),
            "size_bucket": 8 + rng.randrange(4),
            "approx_bytes": 1 << (7 + rng.randrange(4)),
            "deadline_ms": 0.0,
            "priority": rng.random() < 0.10,
            "verdict": "ok",
        })
    return out


# mutable knobs the replay autotuner searches: the admission bounds
# that decide what an overloaded front sheds vs queues
AUTOTUNE_NAMES = frozenset({"LDT_MAX_INFLIGHT", "LDT_MAX_QUEUE_DOCS"})


def bench_replay(capture_dir: str | None = None, speedup: float = 1.0,
                 workers: int = 2, synth: str | None = None,
                 clients: int = 8,
                 autotune_slo: str | None = None) -> dict:
    """`bench.py --replay DIR [--speedup N]` / `--replay-synth
    <stream>`: boot a REUSEPORT fleet and re-drive a capture (or a
    synthetic stream: the original `zipf`, or any loadgen scenario —
    flash_crowd, diurnal, burst_lull, tenant_shift) against it on the
    recorded schedule. With `autotune_slo` set (an LDT_SLO spec
    string), the same booted fleet then hosts an autotune.autotune()
    search: each candidate override batch is pushed fleet-wide through
    the supervisor's POST /configz (probation 0 — the bench drives its
    own scoring, it does not need the canary window) and scored on the
    replayed SLIs; the winning config and the default-vs-autotuned
    comparison land in BENCH_replay.json. Emits BENCH_replay.json."""
    import os
    import signal
    import socket
    import subprocess
    import urllib.request

    from language_detector_tpu import capture as cap

    if synth:
        if synth == "zipf":
            records = synth_capture_records()
        else:
            from language_detector_tpu import loadgen
            if synth not in loadgen.scenario_names():
                raise SystemExit(
                    f"unknown synth stream {synth!r} (have: zipf, "
                    f"{', '.join(loadgen.scenario_names())})")
            records = loadgen.generate(synth)
        source = {"synth": synth, "records": len(records)}
    else:
        records = cap.merge_captures(capture_dir)
        source = {"dir": capture_dir, "records": len(records)}
        if not records:
            raise SystemExit(f"bench --replay: no capture records "
                             f"under {capture_dir}")

    def _free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    port, sport = _free_port(), _free_port()
    env = os.environ.copy()
    env.update({
        "LISTEN_PORT": str(port),
        "PROMETHEUS_PORT": "0",
        "LDT_FLEET_WORKERS": str(workers),
        "LDT_FLEET_STATUS_PORT": str(sport),
        # pin the fleet size: autoscale churn mid-replay would swap
        # cold-cache workers into the measurement and make laps
        # incomparable (the overload scenarios trip the default
        # scale-up depth constantly)
        "LDT_FLEET_SCALE_UP_DEPTH": "0",
    })
    log = open("/tmp/ldt_replay_fleet.log", "w")
    sup = subprocess.Popen(
        [sys.executable, "-m",
         "language_detector_tpu.service.supervisor",
         "language_detector_tpu.service.aioserver"],
        env=env, stdout=log, stderr=subprocess.STDOUT,
        start_new_session=True)
    try:
        deadline = time.time() + 300
        while True:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{sport}/fleetz",
                        timeout=5) as resp:
                    if json.loads(resp.read().decode())["ready"] \
                            == workers:
                        break
            except Exception:  # noqa: BLE001 - still booting
                pass
            if sup.poll() is not None:
                raise RuntimeError(f"replay fleet died rc={sup.poll()}")
            if time.time() > deadline:
                raise RuntimeError("replay fleet never became ready")
            time.sleep(0.2)
        # untimed warm lap over the FULL record set: compiles and
        # shared-cache fills must not be charged to the recorded
        # schedule (nor, in autotune mode, credited to whichever
        # candidate happens to run first)
        replay_records(records, port, speedup=speedup,
                       clients=clients)
        result = replay_records(records, port, speedup=speedup,
                                clients=clients)
        tuned = None
        if autotune_slo:
            from language_detector_tpu import autotune, slo

            spec = slo.parse_spec(autotune_slo)
            tuned_names = sorted(AUTOTUNE_NAMES)

            def _push_config(batch: dict) -> None:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{sport}/configz",
                    data=json.dumps({"set": batch,
                                     "probation_sec": 0}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=60) as resp:
                    resp.read()

            def evaluate(ov: dict) -> dict:
                # full-reset-then-set: knobs the candidate leaves out
                # must fall back to env defaults, not linger from the
                # previous eval's push
                batch = {name: None for name in tuned_names}
                batch.update(ov)
                _push_config(batch)
                # best of two laps: the lap right after a config push
                # pays first-seen batch-composition compiles and cache
                # re-warming that belong to the transition, not the
                # candidate — scoring it alone structurally favors
                # whatever config the fleet happened to be warm on
                m = None
                for _lap in range(2):
                    r = replay_records(records, port, speedup=speedup,
                                       clients=clients)
                    m2 = dict(r["sli"], counts=r["counts"])
                    if m is None or autotune.score(m2, spec) \
                            > autotune.score(m, spec):
                        m = m2
                return m

            tuned = autotune.autotune(evaluate, names=AUTOTUNE_NAMES,
                                      spec=spec)
            # confirmation laps, alternating default/winner on the
            # same fully-warmed fleet: eval-order warm-up (JIT, the
            # fleet-shared result cache) must not be allowed to
            # flatter whichever config happened to run last, and
            # single-lap scheduler noise must not decide the verdict
            confirm: dict = {"default": [], "autotuned": []}
            for _lap in range(3):
                _push_config({name: None for name in tuned_names})
                r = replay_records(records, port, speedup=speedup,
                                   clients=clients)
                confirm["default"].append(r["sli"])
                _push_config(dict({name: None for name in tuned_names},
                                  **tuned["best"]))
                r = replay_records(records, port, speedup=speedup,
                                   clients=clients)
                confirm["autotuned"].append(r["sli"])

            def _mean_sli(laps: list) -> dict:
                return {k: round(sum(lap[k] for lap in laps)
                                 / len(laps), 2)
                        for k in laps[0]}

            tuned["confirm"] = {
                "laps": confirm,
                "default": _mean_sli(confirm["default"]),
                "autotuned": _mean_sli(confirm["autotuned"]),
            }
        sup.send_signal(signal.SIGINT)
        rc = sup.wait(timeout=120)
        if rc != 0:
            result["fleet_exit"] = rc
    finally:
        try:
            os.killpg(sup.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        sup.wait(timeout=30)
        log.close()
    out = dict(metric="service_replay",
               value=result.get("schedule", {}).get("skew_frac_p95",
                                                    1.0),
               unit="p95_skew_frac_of_span",
               detail=dict(source=source, fleet_workers=workers,
                           clients=clients, **result))
    if tuned is not None:
        out["detail"]["autotune"] = dict(scenario=synth or "capture",
                                         slo=autotune_slo, **tuned)
    with open(REPO / "BENCH_replay.json", "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    return out


# capture-plane overhead budget: one record append (struct pack +
# mmap store + commit word + counters) must stay under 1% of a cheap
# request's cost; the --smoke gate recomputes the 1% bound from the
# measured engine throughput and also enforces this absolute ceiling
CAPTURE_BUDGET_NS = 50_000


def bench_capture_overhead(n: int = 4000) -> dict:
    """ns per capture record on the real hot path (module-level
    capture.observe with an armed writer, spans on the trace, counters
    included) — the cost finish_request pays per request when
    LDT_CAPTURE_DIR is set."""
    import shutil
    import tempfile

    from language_detector_tpu import capture as cap
    from language_detector_tpu import telemetry

    tmp = tempfile.mkdtemp(prefix="ldt-bench-cap-")
    saved = cap.WRITER
    try:
        cap.WRITER = cap.CaptureWriter(tmp, ring_records=1024,
                                       sample=1.0, seed=0)
        tr = telemetry.Trace()
        tr.tenant = "bench"
        t = tr.t0
        for stage in ("parse", "detect", "encode"):
            t = telemetry.observe_stage(stage, t, trace=tr)
        meta = {"front": "sync", "docs": 256, "bytes": 40_000,
                "status": 200, "priority": False}
        t0 = time.perf_counter()
        for _ in range(n):
            cap.observe(tr, meta, 4.2)
        record_ns = (time.perf_counter() - t0) * 1e9 / n
        cap.WRITER.close()
    finally:
        cap.WRITER = saved
        shutil.rmtree(tmp, ignore_errors=True)
    telemetry.REGISTRY.reset()
    return {"capture_ns_per_record": round(record_ns, 1)}


if __name__ == "__main__":
    # --profile DIR: wrap the run in a jax.profiler trace (open DIR with
    # tensorboard / xprof to see the device timeline per op)
    # --smoke: small fast configuration (CI sanity, not a benchmark)
    # --multichip [N]: pooled throughput over an N-device virtual mesh
    # --longdoc [N]: span-parallel lane A/B over a fat-tail corpus
    # --fleet [N]: N-worker front-tier saturation vs 1-worker baseline
    # --shm: shared-memory ring lane vs the UDS lane, one sync worker
    # --coldstart [N]: boot-hot A/B — no-cache vs compile-cache vs AOT
    #   cold-to-ready, plus the duplicate-heavy N-member fleet pass
    if len(sys.argv) > 1 and sys.argv[1] == "--longdoc":
        n = int(sys.argv[2]) if len(sys.argv) > 2 else 256
        print(json.dumps(bench_longdoc(n)))
    elif len(sys.argv) > 1 and sys.argv[1] == "--multichip":
        n = int(sys.argv[2]) if len(sys.argv) > 2 else 8
        print(json.dumps(run_multichip(n)))
    elif len(sys.argv) > 1 and sys.argv[1] == "--multichip-child":
        print(json.dumps(bench_multichip_child(int(sys.argv[2]))))
    elif len(sys.argv) > 1 and sys.argv[1] == "--fleet":
        n = int(sys.argv[2]) if len(sys.argv) > 2 else 3
        out = bench_fleet(n)
        with open(REPO / "BENCH_r08.json", "w") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
        print(json.dumps(out))
    elif len(sys.argv) > 1 and sys.argv[1] == "--shm":
        out = bench_shm()
        with open(REPO / "BENCH_r09.json", "w") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
        print(json.dumps(out))
    elif len(sys.argv) > 1 and sys.argv[1] == "--kernel":
        n = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
        out = bench_kernel(n)
        with open(REPO / "BENCH_r10.json", "w") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
        print(json.dumps(out))
    elif len(sys.argv) > 1 and sys.argv[1] == "--coldstart":
        n = int(sys.argv[2]) if len(sys.argv) > 2 else 2
        out = bench_coldstart(fleet_workers=n)
        with open(REPO / "BENCH_r11.json", "w") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
        print(json.dumps(out))
    elif len(sys.argv) > 1 and sys.argv[1] == "--replay":
        if len(sys.argv) < 3:
            sys.exit("usage: bench.py --replay CAPTURE_DIR "
                     "[--speedup N] [--workers N]")
        speedup = 1.0
        workers = 2
        if "--speedup" in sys.argv:
            speedup = float(sys.argv[sys.argv.index("--speedup") + 1])
        if "--workers" in sys.argv:
            workers = int(sys.argv[sys.argv.index("--workers") + 1])
        print(json.dumps(bench_replay(sys.argv[2], speedup=speedup,
                                      workers=workers)))
    elif len(sys.argv) > 1 and sys.argv[1] == "--replay-synth":
        stream = sys.argv[2] if len(sys.argv) > 2 \
            and not sys.argv[2].startswith("--") else "zipf"
        speedup = 1.0
        workers = 2
        clients = 8
        autotune_slo = None
        if "--speedup" in sys.argv:
            speedup = float(sys.argv[sys.argv.index("--speedup") + 1])
        if "--workers" in sys.argv:
            workers = int(sys.argv[sys.argv.index("--workers") + 1])
        if "--clients" in sys.argv:
            clients = int(sys.argv[sys.argv.index("--clients") + 1])
        if "--autotune" in sys.argv:
            # search the admission-knob space against this scenario,
            # scoring on the declared SLO (overridable via --slo)
            autotune_slo = "p99_ms=500,err_pct=1,window_sec=30"
        if "--slo" in sys.argv:
            autotune_slo = sys.argv[sys.argv.index("--slo") + 1]
        print(json.dumps(bench_replay(synth=stream, speedup=speedup,
                                      workers=workers, clients=clients,
                                      autotune_slo=autotune_slo)))
    elif len(sys.argv) > 1 and sys.argv[1] == "--eval":
        # accuracy scorecard (evalsuite.py): batch the bundled labeled
        # corpus through the engine, compare against the scalar oracle
        # doc-for-doc, and publish the vectorized scorecard as the next
        # ACC_rNN.json round (schema: docs/ACCURACY.md). --quick runs
        # the strided subset the ci accuracy smoke uses and only
        # prints the card (no round file — CI cadence must not
        # accrete artifacts). Exits nonzero when top-1 agreement
        # drops below the pinned floor.
        from language_detector_tpu import evalsuite
        quick = "--quick" in sys.argv
        try:
            from language_detector_tpu.models.ngram import \
                NgramBatchEngine
            eng = NgramBatchEngine()
        except (ImportError, RuntimeError):
            eng = None
        card = evalsuite.run_eval(engine=eng, quick=quick)
        if not quick:
            existing = sorted(REPO.glob("ACC_r*.json"))
            nxt = 1
            if existing:
                import re as _re
                m = _re.search(r"ACC_r(\d+)", existing[-1].name)
                nxt = int(m.group(1)) + 1 if m else 1
            card["round"] = nxt
            with open(REPO / f"ACC_r{nxt:02d}.json", "w") as f:
                json.dump(card, f, indent=2)
                f.write("\n")
        print(json.dumps(card))
        evalsuite.check_floor(card)
    elif len(sys.argv) > 1 and sys.argv[1] == "--profile":
        if len(sys.argv) < 3:
            sys.exit("usage: bench.py [--profile TRACE_DIR | --smoke]")
        import jax
        with jax.profiler.trace(sys.argv[2]):
            print(json.dumps(bench(http_bench=False)))
    elif len(sys.argv) > 1 and sys.argv[1] == "--smoke":
        # time the full static-analysis suite first (subprocess: its
        # imports and the model checker's exploration must not warm or
        # pollute this process) and hold it to LINT_BUDGET_MS — the
        # suite runs on every CI pass, so "lint got slow" is a
        # regression the smoke catches, not a vibe
        import subprocess
        t0 = time.time()
        r = subprocess.run(
            [sys.executable, "-m", "tools.lint"], cwd=str(REPO),
            capture_output=True, text=True,
            timeout=10 * LINT_BUDGET_MS / 1e3)
        lint_ms = round((time.time() - t0) * 1e3, 1)
        if r.returncode != 0:
            sys.exit(f"bench --smoke: lint violations:\n"
                     f"{r.stdout}{r.stderr}")
        if lint_ms > LINT_BUDGET_MS:
            sys.exit(f"bench --smoke: lint suite took {lint_ms:.0f}ms "
                     f"(budget {LINT_BUDGET_MS}ms)")
        # telemetry overhead gate: the recorder and tracer ride every
        # request, so their per-record cost is held to a hard budget
        telem = bench_telemetry_overhead()
        for key, ns in telem.items():
            if ns > TELEM_BUDGET_NS:
                sys.exit(f"bench --smoke: {key} = {ns:.0f}ns "
                         f"(budget {TELEM_BUDGET_NS}ns)")
        out = bench(batch_size=2048, n_batches=2, http_bench=False)
        out["detail"]["lint_ms"] = lint_ms
        out["detail"].update(telem)
        # capture-plane overhead gate: one record per request, so its
        # append must cost under 1% of a request — measured against
        # THIS run's engine throughput (a 256-doc request's docs/sec
        # share), with CAPTURE_BUDGET_NS as the absolute ceiling
        capt = bench_capture_overhead()
        docs_sec = out.get("value") or 0
        request_ns = 256 / docs_sec * 1e9 if docs_sec else 0
        budget_ns = min(CAPTURE_BUDGET_NS, request_ns * 0.01) \
            if request_ns else CAPTURE_BUDGET_NS
        if capt["capture_ns_per_record"] > budget_ns:
            sys.exit(f"bench --smoke: capture overhead "
                     f"{capt['capture_ns_per_record']:.0f}ns/record "
                     f"(budget {budget_ns:.0f}ns = min(1% of a "
                     f"256-doc request, {CAPTURE_BUDGET_NS}ns))")
        capt["capture_budget_ns"] = round(budget_ns, 1)
        if request_ns:
            capt["capture_frac_of_request"] = round(
                capt["capture_ns_per_record"] / request_ns, 6)
        out["detail"].update(capt)
        # integrity scrub overhead gate: one scrub+canary cycle,
        # amortized over the scrub interval, must cost under 1% of
        # serving capacity — the data-plane guard must stay invisible
        # in docs/sec
        os.environ.update({"LDT_POOL_LANES": "2",
                           "LDT_SCRUB_INTERVAL_SEC": "30",
                           "LDT_CANARY_DOCS": "8"})
        from language_detector_tpu import integrity
        from language_detector_tpu.models.ngram import NgramBatchEngine
        scrub = integrity.bench_scrub_overhead(NgramBatchEngine())
        if scrub is None:
            sys.exit("bench --smoke: integrity monitor failed to "
                     "build (LDT_SCRUB_INTERVAL_SEC set but no "
                     "monitor)")
        if scrub["overhead_frac"] > SCRUB_BUDGET_FRAC:
            sys.exit(f"bench --smoke: scrub overhead "
                     f"{scrub['overhead_frac']:.4f} of capacity "
                     f"(budget {SCRUB_BUDGET_FRAC}); cycle "
                     f"{scrub['scrub_cycle_ms']}ms per "
                     f"{scrub['interval_ms']:.0f}ms interval")
        out["detail"]["scrub"] = scrub
        print(json.dumps(out))
    else:
        print(json.dumps(bench()))
