"""service/supervisor.py: restart-on-recycle loop, exit-code
propagation, and PID-1 signal forwarding, exercised against the
scriptable tests/fake_worker.py child over real subprocesses."""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

from language_detector_tpu.service.recycle import RECYCLE_EXIT_CODE

REPO = Path(__file__).resolve().parent.parent
SUPERVISOR = [sys.executable, "-m",
              "language_detector_tpu.service.supervisor",
              "tests.fake_worker"]


def _run(env_extra: dict, timeout: float = 30):
    env = dict(os.environ)
    env.update(env_extra)
    return subprocess.run(SUPERVISOR, cwd=REPO, env=env,
                          capture_output=True, text=True,
                          timeout=timeout)


def test_child_exit_code_propagates():
    r = _run({"FAKE_WORKER_EXIT": "5"})
    assert r.returncode == 5
    assert "propagating" in r.stdout


def test_clean_exit_propagates_zero():
    r = _run({"FAKE_WORKER_EXIT": "0"})
    assert r.returncode == 0
    assert "generation 1" in r.stdout
    assert "generation 2" not in r.stdout


def test_recycle_restarts_then_propagates(tmp_path):
    marker = tmp_path / "recycled.marker"
    r = _run({"FAKE_WORKER_RECYCLE": str(marker)})
    # generation 1 exits RECYCLE_EXIT_CODE -> supervisor restarts;
    # generation 2 sees the marker and exits 0, which propagates
    assert r.returncode == 0
    assert marker.exists()
    assert "generation 1" in r.stdout and "generation 2" in r.stdout
    assert "worker recycled" in r.stdout
    assert str(RECYCLE_EXIT_CODE) not in str(r.returncode)


def test_restart_on_crash_recovers(tmp_path):
    counter = tmp_path / "crashes.count"
    r = _run({"FAKE_WORKER_CRASH_UNTIL": f"{counter}:2",
              "LDT_RESTART_ON_CRASH": "1",
              "LDT_CRASH_BACKOFF_BASE_SEC": "0.01",
              "LDT_CRASH_BACKOFF_MAX_SEC": "0.05"})
    # generations 1 and 2 crash (exit 9), generation 3 exits 0
    assert r.returncode == 0, r.stdout + r.stderr
    assert counter.read_text() == "3"
    assert "restarting after backoff" in r.stdout
    for gen in (1, 2, 3):
        assert f"generation {gen}" in r.stdout
    assert "generation 4" not in r.stdout


def test_crash_without_optin_propagates(tmp_path):
    counter = tmp_path / "crashes.count"
    r = _run({"FAKE_WORKER_CRASH_UNTIL": f"{counter}:2"})
    # no LDT_RESTART_ON_CRASH: the first crash propagates immediately
    assert r.returncode == 9
    assert counter.read_text() == "1"
    assert "LDT_RESTART_ON_CRASH" in r.stdout


def test_crash_loop_detected(tmp_path):
    counter = tmp_path / "crashes.count"
    # the worker would need 10 crashes to heal, but the loop detector
    # gives up after 3 inside the window and propagates the exit code
    r = _run({"FAKE_WORKER_CRASH_UNTIL": f"{counter}:10",
              "LDT_RESTART_ON_CRASH": "1",
              "LDT_CRASH_BACKOFF_BASE_SEC": "0.01",
              "LDT_CRASH_BACKOFF_MAX_SEC": "0.05",
              "LDT_CRASH_LOOP_MAX": "3",
              "LDT_CRASH_LOOP_WINDOW_SEC": "60"})
    assert r.returncode == 9
    assert "crash-loop" in r.stdout
    assert counter.read_text() == "3"


def test_generation_env_handed_to_children(tmp_path):
    marker = tmp_path / "recycled.marker"
    r = _run({"FAKE_WORKER_RECYCLE": str(marker)})
    assert r.returncode == 0
    gens = [json.loads(line)["fake_worker_generation"]
            for line in r.stdout.splitlines()
            if "fake_worker_generation" in line]
    assert gens == ["1", "2"]


def test_compile_cache_dir_shared_across_generations(tmp_path):
    """Every spawned generation gets the SAME LDT_COMPILE_CACHE_DIR in
    its env (operator-set here), so generation 2+ warms its bucket
    ladder from generation 1's persisted XLA compiles instead of
    starting cold."""
    marker = tmp_path / "recycled.marker"
    cache = tmp_path / "xla-cache"
    r = _run({"FAKE_WORKER_RECYCLE": str(marker),
              "LDT_COMPILE_CACHE_DIR": str(cache)})
    assert r.returncode == 0
    dirs = [json.loads(line)["fake_worker_cache_dir"]
            for line in r.stdout.splitlines()
            if "fake_worker_cache_dir" in line]
    assert dirs == [str(cache), str(cache)]
    assert cache.is_dir()  # the supervisor created it up front


def test_compile_cache_dir_defaults_per_supervisor(tmp_path):
    """Without the operator knob the supervisor still hands every
    generation one shared per-supervisor cache dir (continuity is the
    default, not an opt-in)."""
    marker = tmp_path / "recycled.marker"
    env = dict(os.environ)
    env.pop("LDT_COMPILE_CACHE_DIR", None)
    env["FAKE_WORKER_RECYCLE"] = str(marker)
    r = subprocess.run(SUPERVISOR, cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=30)
    assert r.returncode == 0
    dirs = [json.loads(line)["fake_worker_cache_dir"]
            for line in r.stdout.splitlines()
            if "fake_worker_cache_dir" in line]
    assert len(dirs) == 2
    assert dirs[0] == dirs[1] != "unset"
    assert "ldt-compile-cache" in dirs[0]


# -- blue/green swap drill (SIGHUP) ------------------------------------------


def _start_serving_supervisor(tmp_path, env_extra=None):
    env = dict(os.environ)
    env["FAKE_WORKER_SERVE"] = str(tmp_path)
    env["LDT_SWAP_TIMEOUT_SEC"] = "20"
    env.update(env_extra or {})
    return subprocess.Popen(SUPERVISOR, cwd=REPO, env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def _wait_for(path: Path, timeout: float = 20) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if path.exists():
            return True
        time.sleep(0.05)
    return False


def _stop(proc) -> str:
    """SIGTERM the supervisor and return its full stdout."""
    try:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=20)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate(timeout=10)
    return out


def test_sighup_swap_drill_promotes_standby(tmp_path):
    proc = _start_serving_supervisor(tmp_path)
    try:
        assert _wait_for(tmp_path / "gen-1.up"), "gen 1 never served"
        proc.send_signal(signal.SIGHUP)
        # the drill spawns generation 2 with the ready-file handshake;
        # once it lands the old generation is drained and gen 2 serves
        assert _wait_for(tmp_path / "gen-2.up"), "standby never spawned"
    finally:
        out = _stop(proc)
    assert proc.returncode == 0, out
    assert "swap drill starting" in out
    assert "swap cutover" in out
    assert "swap complete" in out
    assert "swap-abort" not in out
    # the promoted standby carried the swap env contract
    gens = [json.loads(line)["fake_worker_generation"]
            for line in out.splitlines()
            if "fake_worker_generation" in line]
    assert gens == ["1", "2"]


def test_sighup_swap_aborts_when_standby_dies(tmp_path):
    proc = _start_serving_supervisor(
        tmp_path, {"FAKE_WORKER_STANDBY_CRASH": "1"})
    try:
        assert _wait_for(tmp_path / "gen-1.up")
        proc.send_signal(signal.SIGHUP)
        # the standby starts (drops gen-2.up) then dies before its
        # ready file; give the drill a beat to notice and abort
        assert _wait_for(tmp_path / "gen-2.up")
        time.sleep(1.0)
    finally:
        out = _stop(proc)
    # the old generation kept serving until our SIGTERM — clean exit
    assert proc.returncode == 0, out
    assert "standby died before ready" in out
    assert "swap complete" not in out


def test_sighup_swap_aborts_on_injected_fault(tmp_path):
    proc = _start_serving_supervisor(
        tmp_path, {"LDT_FAULTS": "standby_spawn:error"})
    try:
        assert _wait_for(tmp_path / "gen-1.up")
        proc.send_signal(signal.SIGHUP)
        time.sleep(1.0)  # give the drill a beat to abort
        assert not (tmp_path / "gen-2.up").exists()
    finally:
        out = _stop(proc)
    assert proc.returncode == 0, out
    assert "injected fault" in out
    assert "swap complete" not in out


def test_sighup_swap_artifact_pointer(tmp_path):
    """LDT_ARTIFACT_POINTER names a file whose contents become the
    standby's LDT_ARTIFACT_PATH — the operator flips the pointer, then
    HUPs. An unreadable pointer aborts before any spawn."""
    pointer = tmp_path / "current.txt"
    pointer.write_text(str(tmp_path / "model-v2.ldta"))
    proc = _start_serving_supervisor(
        tmp_path, {"LDT_ARTIFACT_POINTER": str(pointer)})
    try:
        assert _wait_for(tmp_path / "gen-1.up")
        proc.send_signal(signal.SIGHUP)
        assert _wait_for(tmp_path / "gen-2.up")
    finally:
        out = _stop(proc)
    assert proc.returncode == 0, out
    assert "swap complete" in out

    # unreadable pointer: drill aborts, no standby
    missing_dir = tmp_path / "second"
    missing_dir.mkdir()
    proc = _start_serving_supervisor(
        missing_dir,
        {"LDT_ARTIFACT_POINTER": str(tmp_path / "missing.txt")})
    try:
        assert _wait_for(missing_dir / "gen-1.up")
        proc.send_signal(signal.SIGHUP)
        time.sleep(1.0)
        assert not (missing_dir / "gen-2.up").exists()
    finally:
        out = _stop(proc)
    assert proc.returncode == 0, out
    assert "artifact pointer" in out and "swap-abort" in out


# -- fleet supervisor (LDT_FLEET_WORKERS > 0 dispatches to fleet.py) ---------


def _free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _start_fleet(tmp_path, n: int, env_extra=None):
    """(Popen, status_port) for an n-member fake-worker fleet with the
    control-plane endpoint enabled."""
    port = _free_port()
    env = dict(os.environ)
    env["FAKE_WORKER_SERVE"] = str(tmp_path)
    env["LDT_FLEET_WORKERS"] = str(n)
    env["LDT_FLEET_STATUS_PORT"] = str(port)
    env["LDT_SWAP_TIMEOUT_SEC"] = "20"
    env["LDT_CRASH_BACKOFF_BASE_SEC"] = "0.2"
    env["LDT_CRASH_BACKOFF_MAX_SEC"] = "0.5"
    env.update(env_extra or {})
    proc = subprocess.Popen(SUPERVISOR, cwd=REPO, env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    return proc, port


def _fleetz(port: int):
    import urllib.request
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/fleetz", timeout=2) as r:
            return json.loads(r.read())
    except Exception:  # noqa: BLE001 - not up yet / mid-teardown
        return None


def _wait_fleet(port: int, pred, timeout: float = 30):
    """Poll /fleetz until pred(snapshot) holds; the snapshot or None."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        snap = _fleetz(port)
        if snap is not None and pred(snap):
            return snap
        time.sleep(0.05)
    return None


def test_fleet_spawns_n_members_and_drains_clean(tmp_path):
    proc, port = _start_fleet(tmp_path, 3)
    try:
        snap = _wait_fleet(port, lambda s: s["ready"] == 3)
        assert snap, "fleet never reached 3 ready members"
        assert [m["slot"] for m in snap["members"]] == [0, 1, 2]
        assert {m["generation"] for m in snap["members"]} == {1, 2, 3}
        assert snap["circuit"] == "closed" and snap["bootstrapped"]
    finally:
        out = _stop(proc)
    assert proc.returncode == 0, out
    slots = sorted(json.loads(line)["fake_worker_slot"]
                   for line in out.splitlines()
                   if "fake_worker_slot" in line)
    assert slots == ["0", "1", "2"]
    assert '"reason": "fleet-start"' in out


def test_fleet_two_simultaneous_recycles(tmp_path):
    """Both members exiting RECYCLE_EXIT_CODE in the same reap window
    must respawn immediately (no crash accounting, no circuit trip)."""
    proc, port = _start_fleet(tmp_path, 2, {
        "FAKE_WORKER_CRASH_FILE": str(tmp_path / "crash-%SLOT%")})
    try:
        assert _wait_fleet(port, lambda s: s["ready"] == 2)
        (tmp_path / "crash-0").write_text(str(RECYCLE_EXIT_CODE))
        (tmp_path / "crash-1").write_text(str(RECYCLE_EXIT_CODE))
        snap = _wait_fleet(
            port, lambda s: s["ready"] == 2 and
            {m["generation"] for m in s["members"]} == {3, 4})
        assert snap, "fleet never recovered from the double recycle"
        assert snap["circuit"] == "closed"
    finally:
        out = _stop(proc)
    assert proc.returncode == 0, out
    assert out.count('"reason": "recycle"') == 2
    assert '"fleet-circuit-open"' not in out


def test_fleet_sigterm_during_rolling_swap(tmp_path):
    """SIGHUP roll in flight, then SIGTERM (and another SIGHUP for good
    measure): the roll aborts, the standby is killed, every member
    drains, exit 0 — the N>1 generalization of the signal-race
    contract."""
    proc, port = _start_fleet(tmp_path, 2, {
        "FAKE_WORKER_READY_DELAY": "1.0"})
    try:
        assert _wait_fleet(port, lambda s: s["ready"] == 2)
        proc.send_signal(signal.SIGHUP)
        # the slot-0 standby (generation 3) starts, then holds in its
        # ready delay — SIGTERM lands inside the roll window
        assert _wait_for(tmp_path / "gen-3.up"), "standby never spawned"
        proc.send_signal(signal.SIGTERM)
        proc.send_signal(signal.SIGHUP)   # queued swap must be ignored
    finally:
        out = _stop(proc)
    assert proc.returncode == 0, out
    assert '"swap-abort"' in out
    assert '"reason": "signal"' in out
    assert "rolling swap complete" not in out


def test_fleet_member_death_during_rolling_swap(tmp_path):
    """A member dying while another slot is mid-roll: the roll for the
    rolling slot completes, the dead member is reaped and respawned,
    and the fleet returns to full strength."""
    proc, port = _start_fleet(tmp_path, 2, {
        "FAKE_WORKER_READY_DELAY": "1.0",
        "FAKE_WORKER_CRASH_FILE": str(tmp_path / "crash-%SLOT%")})
    try:
        assert _wait_fleet(port, lambda s: s["ready"] == 2)
        proc.send_signal(signal.SIGHUP)
        assert _wait_for(tmp_path / "gen-3.up"), "standby never spawned"
        (tmp_path / "crash-1").write_text("9")    # dies mid-roll
        snap = _wait_fleet(
            port, lambda s: s["ready"] == 2 and
            {m["generation"] for m in s["members"]} == {3, 4},
            timeout=40)
        assert snap, "fleet never healed after the mid-roll death"
    finally:
        out = _stop(proc)
    assert proc.returncode == 0, out
    assert "roll complete" in out
    assert '"reason": "crash"' in out


def test_fleet_crash_loop_parks_member_and_circuit_recovers(tmp_path):
    """Per-member crash-loop parks the flapping slot; the SAME two
    crashes counted fleet-wide trip the circuit; the cooldown probe
    sees the surviving member still accepting and closes it again."""
    proc, port = _start_fleet(tmp_path, 2, {
        "FAKE_WORKER_CRASH_FILE": str(tmp_path / "crash-%SLOT%"),
        "LDT_CRASH_LOOP_MAX": "2",
        "LDT_CRASH_LOOP_WINDOW_SEC": "60",
        "LDT_FLEET_CIRCUIT_COOLDOWN_SEC": "0.5"})
    try:
        assert _wait_fleet(port, lambda s: s["ready"] == 2)
        (tmp_path / "crash-0").write_text("9")
        # first crash: below the loop max, slot 0 respawns
        assert _wait_fleet(port, lambda s: any(
            m["slot"] == 0 and m["generation"] == 3
            and m["state"] == "ready" for m in s["members"]))
        (tmp_path / "crash-0").write_text("9")
        snap = _wait_fleet(port, lambda s: any(
            m["slot"] == 0 and m["parked"] for m in s["members"]))
        assert snap, "slot 0 never parked after its crash loop"
        snap = _wait_fleet(port, lambda s: s["circuit"] == "closed")
        assert snap, "circuit never closed after the cooldown probe"
        assert any(m["slot"] == 1 and m["state"] == "ready"
                   for m in snap["members"])
        assert not any(m["slot"] == 0 and m["state"] == "ready"
                       for m in snap["members"])
    finally:
        out = _stop(proc)
    assert proc.returncode == 0, out
    assert '"reason": "crash-loop"' in out
    assert '"fleet-circuit-open"' in out
    assert '"fleet-circuit-close"' in out


def test_fleet_spawn_fault_retries_after_backoff(tmp_path):
    """worker_spawn fault point: the injected spawn failure costs one
    attempt, the member retries after backoff, the fleet still reaches
    full strength."""
    proc, port = _start_fleet(tmp_path, 2, {
        "LDT_FAULTS": "worker_spawn:error:once"})
    try:
        assert _wait_fleet(port, lambda s: s["ready"] == 2), \
            "fleet never recovered from the injected spawn failure"
    finally:
        out = _stop(proc)
    assert proc.returncode == 0, out
    assert '"reason": "spawn-failed"' in out


def test_fleet_worker_lost_fault_fails_over(tmp_path):
    """worker_lost fault point: a silently-lost member is SIGKILLed by
    the seam, treated as a crash, and replaced; the loss shows up on
    the status /metrics exposition."""
    import urllib.request
    proc, port = _start_fleet(tmp_path, 2, {
        "LDT_FAULTS": "worker_lost:error:once"})
    try:
        snap = _wait_fleet(
            port, lambda s: s["ready"] == 2 and
            max(m["generation"] for m in s["members"]) >= 3)
        assert snap, "fleet never replaced the lost member"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
            metrics = r.read().decode()
        assert 'ldt_fleet_worker_lost_total{reason="lost"} 1' in metrics
        assert "ldt_fleet_ready 2" in metrics
    finally:
        out = _stop(proc)
    assert proc.returncode == 0, out


# -- restart cold-start: shared persistent compile cache ---------------------


# The exact warmup the fronts run under LDT_WARMUP (DetectorService
# .warm()'s corpus), timed in a worker-like subprocess: generation 1
# populates LDT_COMPILE_CACHE_DIR, generation 2 must start warm from it.
_WARM_SNIPPET = r"""
import json, time
from language_detector_tpu.models.ngram import NgramBatchEngine
eng = NgramBatchEngine()
base = ("the quick brown fox jumps over the lazy dog ",
        "el veloz murcielago hindu comia feliz cardillo ",
        "portez ce vieux whisky au juge blond qui fume ")
texts = [base[i % 3] * (1 + (i % 4) * 8) + str(i) for i in range(96)]
t0 = time.monotonic()
eng.detect_codes(texts)
print(json.dumps({"warmup_ms": (time.monotonic() - t0) * 1e3}))
"""


def test_generation2_warmup_substantially_below_generation1(tmp_path):
    """The restart cold-start fix end to end: two fresh processes (the
    supervisor's generation 1 and 2) sharing one LDT_COMPILE_CACHE_DIR;
    the second's warmup must come in far under the first's, because its
    bucket-ladder programs deserialize from the persistent XLA cache
    instead of recompiling."""
    from language_detector_tpu import native
    if not native.available():
        pytest.skip("native packer unavailable")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["LDT_COMPILE_CACHE_DIR"] = str(tmp_path / "xla-cache")
    env.pop("LDT_POOL_LANES", None)

    def generation() -> float:
        r = subprocess.run([sys.executable, "-c", _WARM_SNIPPET],
                           cwd=REPO, env=env, capture_output=True,
                           text=True, timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        return json.loads(r.stdout.splitlines()[-1])["warmup_ms"]

    first = generation()
    second = generation()
    assert second < 0.6 * first, (first, second)


@pytest.mark.parametrize("signum", [signal.SIGTERM, signal.SIGINT])
def test_signal_forwarded_to_child(tmp_path, signum):
    sigfile = tmp_path / "sig.txt"
    env = dict(os.environ)
    env["FAKE_WORKER_SIGFILE"] = str(sigfile)
    proc = subprocess.Popen(SUPERVISOR, cwd=REPO, env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    try:
        ready = sigfile.with_suffix(".txt.ready")
        deadline = time.time() + 20
        while not ready.exists() and time.time() < deadline:
            time.sleep(0.05)
        assert ready.exists(), "worker never became ready"
        proc.send_signal(signum)
        rc = proc.wait(timeout=20)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
    # the worker received the forwarded signal, wrote it down, and
    # exited 0 — which the supervisor propagates without restarting
    assert sigfile.read_text() == str(int(signum))
    assert rc == 0
