"""Bad fixture: every publish-order failure mode, seeded — one
function per message family."""
import struct

HDR = struct.Struct("<IId")
SEQ = struct.Struct("<I")


def bad_write_after_commit(mm, off, rec, payload):
    mm[off + 4:off + HDR.size] = rec[4:]
    mm[off:off + 4] = rec[:4]
    mm[off + HDR.size:off + HDR.size + len(payload)] = payload


def bad_commit_first(mm, off, rec, payload):
    mm[off:off + 4] = rec[:4]
    mm[off + HDR.size:off + HDR.size + len(payload)] = payload


def bad_never_commit(mm, off, rec, payload):
    mm[off + 4:off + HDR.size] = rec[4:]
    mm[off + HDR.size:off + HDR.size + len(payload)] = payload


class SeqBad:
    def put(self, mm, off, payload, s):
        # fields land before any claim: readers can observe a torn
        # record under an even (valid-looking) seq
        HDR.pack_into(mm, off, s + 1, len(payload), 0.0)
        mm[off + HDR.size:off + HDR.size + len(payload)] = payload
        SEQ.pack_into(mm, off, s + 2)


def bad_reader_no_commit(mm, off):
    return mm[off + HDR.size:off + HDR.size + 8]


def bad_reader_unguarded(mm, off):
    seq, length, _ts = HDR.unpack_from(mm, off)
    return mm[off + HDR.size:off + HDR.size + length]
