"""Crash-safe shared-memory ring ingest lane (LDT_SHM_DIR).

The zero-serialization twin of the unix-socket frame lane: a co-located
client writes request bodies straight into a mmap'd, length-prefixed
SPSC ring file and the worker parses them *in place* (the wire fast
scanner slices doc strings directly off the shared mapping — the frame
bytes are never copied into a per-request buffer, so leased frames feed
the pack staging rings with no host-side copy). A ring is shared with
an untrusted client process, which makes this above all a robustness
problem; the protocol is built so that no client crash, worker crash,
fleet roll, or malformed frame can wedge a slot:

  - Slot lifecycle FREE -> WRITING -> READY -> LEASED -> DONE (machine
    "shm-slot" in tools/lint/fsm_registry.py; RingSlot below is the
    in-process mirror whose guarded writes the conformance pass proves
    against the table, and the `ring-reclaim` model-check product
    drives client-crash x worker-crash x generation-bump interleavings
    over it).
  - Generation fencing: the worker bumps the ring header's generation
    on every attach, and clients stamp each frame with the generation
    they observed. A restarted worker (or a fleet roll re-attaching a
    member's ring directory) fails every stale READY/LEASED frame back
    to the client with an explicit error frame — never a hang.
  - Lease reclaim: every slot state carries the writer's PID and a
    lease timestamp. A client killed mid-WRITING is reclaimed to FREE
    once its PID is gone or LDT_SHM_LEASE_TIMEOUT_SEC elapses; a DONE
    frame whose client never returned is reclaimed the same way, and a
    fully-FREE ring with a dead client is unlinked.
  - Poison-frame quarantine: a frame whose docs deterministically kill
    a scorer batch is bisected down to the exact poison docs, which are
    quarantined (answered "un", skipped on re-submission) instead of
    burning pool redispatch budget — `ldt_quarantine_*` series and
    /debug/vars "quarantine".

File layout (little-endian, one page of headers + page-aligned slots so
each slot payload can be mapped at offset 0 of its own mmap):

  0     ring header:  u32 magic "LDSR", u32 version, u32 generation,
                      u32 nslots, u32 client_pid, u32 worker_pid,
                      u64 slot_bytes
  64+i*64  slot i header: u32 state, u32 generation, u32 owner_pid,
                      u32 reserved, f64 lease_ts, u32 length,
                      u32 status; at byte 32 of the 64-byte header
                      region, u32 crc32(payload) when LDT_WIRE_CRC
                      is set on both sides (zero otherwise)
  4096+i*slot_bytes  slot i payload (request body in READY, response
                      body in DONE — same JSON contract as the UDS
                      frame lane, byte-identical responses)

Fault points: shm_attach (worker ring attach), shm_lease (frame lease),
shm_reclaim (reclaim/fence sweep), poison_doc (scorer-kill seam for the
quarantine drills). Run a client via RingClient; both fronts start a
ShmRingServer when LDT_SHM_DIR is set, and fleet.py gives each member
its own ring directory under it.
"""
from __future__ import annotations

import hashlib
import json
import mmap
import os
import random
import struct
import threading
import time
import zlib
from concurrent.futures import TimeoutError as FuturesTimeout

from .. import faults, flightrec, knobs, telemetry
from ..locks import make_lock
from . import wire
from .admission import DeadlineExceeded

RING_MAGIC = 0x5253444C          # "LDSR"
RING_VERSION = 1
HEADER_PAGE = 4096               # ring + slot headers live in page 0
SLOT_HDR_OFF = 64                # first slot header
SLOT_HDR_SIZE = 64
MAX_SLOTS = (HEADER_PAGE - SLOT_HDR_OFF) // SLOT_HDR_SIZE
_PAGE = mmap.ALLOCATIONGRANULARITY or 4096

RING_HDR = struct.Struct("<IIIIII Q")    # magic, version, generation,
#                                          nslots, client_pid,
#                                          worker_pid, slot_bytes
SLOT_HDR = struct.Struct("<IIII d II")   # state, generation, owner_pid,
#                                          reserved, lease_ts, length,
#                                          status

# pinned shm geometry: a drive-by field edit must fail at import, not
# tear slots under every attached peer
# (tools/lint/layout_registry.py declares the same widths)
assert RING_HDR.size == 32
assert SLOT_HDR.size == 32

# Slot lifecycle states, declared in tools/lint/fsm_registry.py
# (machine "shm-slot"): RingSlot.state only moves through the guarded
# mark_* methods below, so the conformance pass proves every write
# against the declared table, and the `ring-reclaim` model-check
# product explores the crash/fence interleavings over the same class.
SLOT_FREE = 0     # unowned, reusable
SLOT_WRITING = 1  # client mid-write (owner_pid = client)
SLOT_READY = 2    # frame committed, waiting for a lease
SLOT_LEASED = 3   # worker scoring the frame (owner_pid = worker)
SLOT_DONE = 4     # response (or error frame) written, client to consume

SLOT_STATE_NAMES = {SLOT_FREE: "free", SLOT_WRITING: "writing",
                    SLOT_READY: "ready", SLOT_LEASED: "leased",
                    SLOT_DONE: "done"}

# explicit error frames (the fail-back contract: a fenced or orphaned
# frame always answers, never hangs the client)
FENCED_BODY = json.dumps(
    {"error": "shm ring fenced: worker generation changed mid-frame; "
              "resubmit"}).encode()
RESP_OVERFLOW_BODY = json.dumps(
    {"error": "response exceeds slot capacity"}).encode()

# poison drill marker: with the poison_doc fault armed, any frame doc
# containing this literal deterministically kills its scorer batch, so
# tests and the ci chaos smoke exercise the real bisection path
POISON_MARKER = "__ldt_poison__"


class RingError(RuntimeError):
    """A ring file that cannot be attached (bad magic/version, or a
    geometry that disagrees with the file size)."""


class RingSlot:
    """Pure in-process mirror of one slot's lifecycle state.

    Both sides of the ring keep a mirror per slot and replay every
    observed cross-process state change through these guarded writes
    (see _advance_mirror): an observed change that no legal transition
    path can explain is a protocol violation and the slot is
    force-reclaimed. The class is deliberately I/O-free so the
    `ring-reclaim` model-check product drives it directly."""

    def __init__(self, index: int):
        self.index = index
        self.state = SLOT_FREE

    # -- guarded FSM writes (one declared transition per branch) ------

    def mark_writing(self) -> None:
        if self.state == SLOT_FREE:
            self.state = SLOT_WRITING

    def mark_ready(self) -> None:
        if self.state == SLOT_WRITING:
            self.state = SLOT_READY

    def mark_leased(self) -> None:
        if self.state == SLOT_READY:
            self.state = SLOT_LEASED

    def mark_done(self) -> None:
        if self.state == SLOT_LEASED:
            self.state = SLOT_DONE

    def mark_failed(self) -> None:
        """Fail-back: a fenced READY frame or an orphaned LEASED frame
        moves to DONE carrying an explicit error frame."""
        if self.state == SLOT_READY:
            self.state = SLOT_DONE
        elif self.state == SLOT_LEASED:
            self.state = SLOT_DONE

    def mark_free(self) -> None:
        """Consume (DONE) or reclaim (a dead client's WRITING)."""
        if self.state == SLOT_DONE:
            self.state = SLOT_FREE
        elif self.state == SLOT_WRITING:
            self.state = SLOT_FREE


def _advance_mirror(s: RingSlot, raw: int) -> bool:
    """Replay the mirror through declared transitions until it matches
    the observed raw state. Returns False when no legal path reaches
    `raw` (a corrupt header) — the caller force-reclaims the slot."""
    for _ in range(6):
        if s.state == raw:
            return True
        if s.state == SLOT_FREE:
            s.mark_writing()
        elif s.state == SLOT_WRITING:
            if raw == SLOT_FREE:
                s.mark_free()
            else:
                s.mark_ready()
        elif s.state == SLOT_READY:
            if raw == SLOT_DONE:
                s.mark_failed()
            else:
                s.mark_leased()
        elif s.state == SLOT_LEASED:
            s.mark_done()
        else:
            s.mark_free()
    return s.state == raw


def _force_free(s: RingSlot) -> None:
    """Walk the mirror to FREE along declared transitions (reclaim)."""
    s.mark_failed()   # READY / LEASED -> DONE
    s.mark_free()     # DONE / WRITING -> FREE


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


def _geometry(slots: int | None, slot_bytes: int | None) -> tuple:
    n = slots or knobs.get_int("LDT_SHM_SLOTS") or 8
    n = max(1, min(int(n), MAX_SLOTS))
    sb = slot_bytes or knobs.get_int("LDT_SHM_SLOT_BYTES") or 65536
    sb = max(int(sb), _PAGE)
    sb = -(-sb // _PAGE) * _PAGE      # page multiple: payloads map at
    return n, sb                      # offset 0 of their own mmap


def lease_timeout_sec() -> float:
    return knobs.get_float("LDT_SHM_LEASE_TIMEOUT_SEC") or 2.0


# ---------------------------------------------------------------------
# ring file mapping (shared by client and worker)


class RingFile:
    """One mmap'd ring file: header accessors over the shared mapping.
    Single-threaded by contract on each side (SPSC): the client object
    is confined to its caller, the worker side to the scan thread."""

    def __init__(self, path: str, create: bool = False,
                 slots: int | None = None,
                 slot_bytes: int | None = None):
        self.path = path
        if create:
            n, sb = _geometry(slots, slot_bytes)
            total = HEADER_PAGE + n * sb
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.truncate(total)
                f.seek(0)
                f.write(RING_HDR.pack(RING_MAGIC, RING_VERSION, 0, n,
                                      os.getpid(), 0, sb))
            os.replace(tmp, path)     # scanners only see whole rings
        self._f = open(path, "r+b")
        size = os.fstat(self._f.fileno()).st_size
        if size < HEADER_PAGE:
            self._f.close()
            raise RingError(f"{path}: shorter than the header page")
        self.mm = mmap.mmap(self._f.fileno(), 0)
        magic, version, _gen, n, _cp, _wp, sb = \
            RING_HDR.unpack_from(self.mm, 0)
        if magic != RING_MAGIC or version != RING_VERSION:
            self.close()
            raise RingError(f"{path}: not an LDSR v{RING_VERSION} ring")
        if not 1 <= n <= MAX_SLOTS or sb % _PAGE or \
                size != HEADER_PAGE + n * sb:
            self.close()
            raise RingError(f"{path}: geometry disagrees with file size")
        self.nslots = n
        self.slot_bytes = sb

    # -- ring header --------------------------------------------------

    @property
    def generation(self) -> int:
        return RING_HDR.unpack_from(self.mm, 0)[2]

    @property
    def client_pid(self) -> int:
        return RING_HDR.unpack_from(self.mm, 0)[4]

    @property
    def worker_pid(self) -> int:
        return RING_HDR.unpack_from(self.mm, 0)[5]

    def set_generation(self, gen: int, worker_pid: int) -> None:
        magic, version, _g, n, cp, _wp, sb = \
            RING_HDR.unpack_from(self.mm, 0)
        RING_HDR.pack_into(self.mm, 0, magic, version, gen, n, cp,
                           worker_pid, sb)

    # -- slot headers -------------------------------------------------

    def read_slot(self, i: int) -> tuple:
        """(state, generation, owner_pid, lease_ts, length, status)."""
        st, gen, pid, _r, ts, ln, status = SLOT_HDR.unpack_from(
            self.mm, SLOT_HDR_OFF + i * SLOT_HDR_SIZE)
        return st, gen, pid, ts, ln, status

    def slot_request_id(self, i: int) -> int:
        """The slot's correlation-id word (the u32 the client stamped
        on submit and the worker echoes on DONE); 0 = no id."""
        return SLOT_HDR.unpack_from(
            self.mm, SLOT_HDR_OFF + i * SLOT_HDR_SIZE)[3]

    def write_slot(self, i: int, state: int, gen: int, pid: int,
                   ts: float, length: int, status: int,
                   reqid: int = 0) -> None:
        # publish order matters: the peer polls the state word, so every
        # other field must land BEFORE it. A single pack_into is a
        # forward memcpy — state first — and a reader could observe the
        # new state with the OLD length/status still in place (a torn
        # frame). Writing the state word last, as its own aligned
        # 4-byte store, makes the state transition the publication
        # point.
        off = SLOT_HDR_OFF + i * SLOT_HDR_SIZE
        rec = SLOT_HDR.pack(state, gen, pid, reqid, ts, length, status)
        self.mm[off + 4:off + SLOT_HDR.size] = rec[4:]
        self.mm[off:off + 4] = rec[:4]

    def write_crc(self, i: int, crc: int) -> None:
        """Stamp the slot's payload-guard word (u32 crc32 at byte 32 of
        the header region). Written BEFORE the READY publish so a
        reader that observes READY sees a settled crc."""
        struct.pack_into("<I", self.mm,
                         SLOT_HDR_OFF + i * SLOT_HDR_SIZE
                         + SLOT_HDR.size, crc)

    def read_crc(self, i: int) -> int:
        return struct.unpack_from(
            "<I", self.mm,
            SLOT_HDR_OFF + i * SLOT_HDR_SIZE + SLOT_HDR.size)[0]

    def payload_off(self, i: int) -> int:
        return HEADER_PAGE + i * self.slot_bytes

    def write_payload(self, i: int, chunks) -> int:
        pos = self.payload_off(i)
        start = pos
        for b in chunks:
            self.mm[pos:pos + len(b)] = b
            pos += len(b)
        return pos - start

    def read_payload(self, i: int, length: int) -> bytes:
        off = self.payload_off(i)
        return self.mm[off:off + length]

    def close(self) -> None:
        try:
            self.mm.close()
        except (BufferError, ValueError):
            pass
        try:
            self._f.close()
        except OSError:
            pass


# ---------------------------------------------------------------------
# client side (producer)


def client_ring_path(shm_dir: str, pid: int | None = None) -> str:
    return os.path.join(shm_dir, f"client-{pid or os.getpid()}.ring")


class RingClient:
    """Producer side of one SPSC ring: creates the ring file in the
    worker's LDT_SHM_DIR, writes request frames into FREE slots and
    collects responses from DONE slots. Confined to a single caller
    thread (SPSC contract) — no locks."""

    def __init__(self, shm_dir: str, slots: int | None = None,
                 slot_bytes: int | None = None,
                 path: str | None = None):
        os.makedirs(shm_dir, exist_ok=True)
        self.path = path or client_ring_path(shm_dir)
        self.rf = RingFile(self.path, create=True, slots=slots,
                           slot_bytes=slot_bytes)
        self.slots = [RingSlot(i) for i in range(self.rf.nslots)]
        self.last_request_id: str | None = None  # echo of last wait()

    def _refresh(self, i: int) -> tuple:
        raw = self.rf.read_slot(i)
        if not _advance_mirror(self.slots[i], raw[0]):
            # the worker force-reclaimed (or the header tore): resync
            _force_free(self.slots[i])
            self.rf.write_slot(i, SLOT_FREE, 0, 0, 0.0, 0, 0)
        return raw

    def attached(self) -> bool:
        """True once a worker has adopted this ring (attach bumps the
        generation past the client's initial 0)."""
        return self.rf.generation > 0

    def wait_attached(self, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        while not self.attached():
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"no worker attached {self.path} within {timeout}s")
            time.sleep(0.001)

    def submit(self, body: bytes,
               request_id: str | None = None) -> int | None:
        """Write one frame into a FREE slot -> slot index, or None when
        the ring is full (the caller drains with wait() first) or no
        worker has attached yet (a frame stamped with the pre-attach
        generation would only be fenced). The shm lane's correlation
        id is natively the slot header's u32: request_id must be its
        1-8 hex-char rendering (the same shape server-generated ids
        use on every lane); the worker echoes it on the DONE header."""
        if len(body) > self.rf.slot_bytes:
            raise ValueError(
                f"frame of {len(body)} bytes exceeds slot capacity "
                f"{self.rf.slot_bytes}")
        reqid = 0
        if request_id is not None:
            try:
                reqid = int(request_id, 16)
            except ValueError:
                reqid = -1
            if not 0 < reqid <= 0xFFFFFFFF:
                raise ValueError(
                    "shm lane request_id must be 1-8 hex chars "
                    f"(u32 slot-header carrier), got {request_id!r}")
        if not self.attached():
            return None
        for i, s in enumerate(self.slots):
            raw = self._refresh(i)
            if self.slots[i].state != SLOT_FREE:
                continue
            del raw
            gen = self.rf.generation   # stamp what we observed: a
            now = time.time()          # worker restart mid-frame fences
            s.mark_writing()
            self.rf.write_slot(i, SLOT_WRITING, gen, os.getpid(), now,
                               0, 0, reqid=reqid)
            self.rf.write_payload(i, (body,))
            if knobs.get_bool("LDT_WIRE_CRC"):
                # guard word must settle before the READY publish:
                # the worker reads it only after observing READY
                self.rf.write_crc(i, zlib.crc32(body))
            s.mark_ready()
            self.rf.write_slot(i, SLOT_READY, gen, os.getpid(), now,
                               len(body), 0, reqid=reqid)
            return i
        return None

    def wait(self, i: int, timeout: float = 30.0) -> tuple:
        """Block (poll) until slot i answers -> (status, body bytes).
        Raises TimeoutError past `timeout` — the protocol's reclaim and
        fencing are designed to make that unreachable for a live
        worker, and the chaos tests pin it.

        The poll backs off exponentially (20us -> 1ms): on a machine
        with fewer cores than processes, a tight fixed-interval spin
        steals the very CPU the worker needs to answer the frame, while
        a pipelining client that keeps other slots READY loses nothing
        to a late wake-up."""
        deadline = time.monotonic() + timeout
        nap = 2e-5
        while True:
            st, _gen, _pid, _ts, length, status = self._refresh(i)
            if self.slots[i].state == SLOT_DONE:
                # surface the DONE header's echoed correlation id (the
                # SPSC contract confines this attribute to the caller)
                rq = self.rf.slot_request_id(i)
                self.last_request_id = ("%08x" % rq) if rq else None
                body = self.rf.read_payload(i, length)
                self.slots[i].mark_free()
                self.rf.write_slot(i, SLOT_FREE, 0, 0, 0.0, 0, 0)
                return status, body
            if self.slots[i].state == SLOT_FREE:
                # reclaimed under us (fence + dead-client sweep raced
                # our poll): surface as an explicit error, not a hang
                return 503, FENCED_BODY
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"slot {i} still {SLOT_STATE_NAMES.get(st, st)} "
                    f"after {timeout}s")
            time.sleep(nap)
            nap = min(nap * 2, 1e-3)

    def request(self, body: bytes, timeout: float = 30.0,
                request_id: str | None = None) -> tuple:
        """submit + wait convenience for sequential callers."""
        deadline = time.monotonic() + timeout
        while True:
            i = self.submit(body, request_id=request_id)
            if i is not None:
                return self.wait(i, timeout=timeout)
            if time.monotonic() >= deadline:
                raise TimeoutError("ring full: no slot freed in time")
            time.sleep(0.0002)

    def close(self, unlink: bool = False) -> None:
        self.rf.close()
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass


# ---------------------------------------------------------------------
# quarantine (poison-doc registry)


class Quarantine:
    """Registry of docs proven to deterministically kill a scorer
    batch. Shared between the scan thread (add/known during bisection)
    and the metrics/debug threads (stats), so the dict lives under its
    own lock (tools/lint/ownership.py)."""

    def __init__(self):
        self._lock = make_lock("shmring.quarantine")
        self._docs: dict = {}     # digest -> hit count
        self.total = 0            # docs quarantined (unique)
        self.bisects = 0          # bisection batch retries

    @staticmethod
    def _digest(text: str) -> str:
        return hashlib.sha1(text.encode("utf-8", "replace")).hexdigest()

    def add(self, text: str) -> bool:
        """Quarantine one doc; True when it is newly quarantined."""
        d = self._digest(text)
        with self._lock:
            fresh = d not in self._docs
            self._docs[d] = self._docs.get(d, 0) + 1
            if fresh:
                self.total += 1
            return fresh

    def known(self, text: str) -> bool:
        d = self._digest(text)
        with self._lock:
            hit = d in self._docs
            if hit:
                self._docs[d] += 1
            return hit

    def note_bisect(self) -> None:
        with self._lock:
            self.bisects += 1

    def stats(self) -> dict:
        with self._lock:
            return {"quarantined_docs": self.total,
                    "bisect_batches": self.bisects,
                    "hits": sum(self._docs.values()) - self.total}


# ---------------------------------------------------------------------
# worker side (consumer)


class _WorkerRing:
    """Worker-side attachment state for one ring: the shared header
    mapping plus one offset-mmap per slot payload, so each frame body
    parses in place starting at offset 0 (wire.fast_parse_texts slices
    doc strings straight off the mapping — zero copy into the pack
    staging path)."""

    def __init__(self, rf: RingFile):
        self.rf = rf
        self.mirrors = [RingSlot(i) for i in range(rf.nslots)]
        self.pmaps = [
            mmap.mmap(rf._f.fileno(), rf.slot_bytes,
                      offset=rf.payload_off(i))
            for i in range(rf.nslots)]
        self.generation = rf.generation

    def close(self) -> None:
        for p in self.pmaps:
            try:
                p.close()
            except (BufferError, ValueError):
                pass
        self.rf.close()


class ShmRingServer:
    """Directory scanner + frame pump + reclaim sweep, one daemon
    thread (the SPSC consumer for every attached ring). Frames parse
    and answer in place on the slot's own mmap — no socket syscalls,
    no frame copies — and a pipelining client keeps the other slots
    full while one scores, so the sweep almost never sleeps under
    load. All mutable state is confined to the scan thread; stats()
    readers get the immutable snapshot dict republished each sweep
    (the FleetStatus confinement argument — a dict rebind is one
    GIL-atomic store)."""

    def __init__(self, svc, shm_dir: str | None = None, detect=None):
        self.svc = svc
        self.dir = shm_dir or knobs.get_str("LDT_SHM_DIR")
        self._base_detect = detect
        self.quarantine = Quarantine()
        self._rings: dict = {}        # path -> _WorkerRing
        self._bad: dict = {}          # path -> mtime of refused file
        self._closing = False
        self._thread: threading.Thread | None = None
        self._stat_lock = make_lock("shmring.stats")
        self._frames = 0
        self._snap: dict = {"rings": 0, "slots_total": 0,
                            "slots_free": 0, "frames": 0}
        self._detect = self._make_detect()

    # -- scoring with poison bisection --------------------------------

    def _make_detect(self):
        svc = self.svc
        base = self._base_detect
        q = self.quarantine

        def score(texts, trace=None):
            # poison_doc drill seam: with the fault armed, any marked
            # doc deterministically kills its batch — the same code
            # path a real deterministic scorer kill takes
            if faults.ACTIVE is not None and \
                    any(POISON_MARKER in t for t in texts):
                faults.hit("poison_doc")
            fn = base if base is not None else svc.detect_codes
            return fn(texts, trace=trace)

        def detect(texts, trace=None):
            if q.total:
                # known-poison pre-filter: a quarantined doc never
                # reaches the scorer again (no redispatch budget burned)
                keep = [i for i, t in enumerate(texts)
                        if not q.known(t)]
                if len(keep) != len(texts):
                    out = ["un"] * len(texts)
                    sub = [texts[i] for i in keep]
                    codes = self._score_or_bisect(sub, trace, score) \
                        if sub else []
                    for i, c in zip(keep, codes):
                        out[i] = c
                    return out
            return self._score_or_bisect(texts, trace, score)

        return detect

    def _score_or_bisect(self, texts, trace, score):
        try:
            return score(texts, trace=trace)
        except (DeadlineExceeded, TimeoutError, FuturesTimeout):
            raise          # backend wedged/expired, not a poison frame
        except Exception:  # noqa: BLE001 - bisect isolates the doc
            return self._bisect(texts, trace, score)

    def _bisect(self, texts, trace, score):
        """A batch the scorer killed: split until the poison docs are
        isolated and quarantined; every healthy doc still answers."""
        self.quarantine.note_bisect()
        telemetry.REGISTRY.counter_inc("ldt_quarantine_bisect_total")
        if len(texts) == 1:
            if self.quarantine.add(texts[0]):
                telemetry.REGISTRY.counter_inc(
                    "ldt_quarantine_docs_total")
                flightrec.emit_event("shm_ring_state",
                                     state="quarantined")
            return ["un"]
        mid = len(texts) // 2
        out: list = []
        for part in (texts[:mid], texts[mid:]):
            try:
                out.extend(score(part, trace=trace))
            except (DeadlineExceeded, TimeoutError, FuturesTimeout):
                raise
            except Exception:  # noqa: BLE001 - recurse on the half
                out.extend(self._bisect(part, trace, score))
        return out

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        m = getattr(self.svc, "metrics", None)
        if m is not None:
            m.shm_stats = self.stats
            m.quarantine_stats = self.quarantine.stats
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ldt-shm-scan")
        self._thread.start()

    def close(self, drain_sec: float = 0.0) -> None:
        self._closing = True
        if self._thread is not None:
            self._thread.join(max(drain_sec, 0.2))
        for ring in self._rings.values():
            ring.close()
        self._rings.clear()

    def stats(self) -> dict:
        # slot/ring counts come from the sweep's snapshot; the frame
        # count reads live (pool jobs increment it between sweeps, and
        # a client can observe its response before the next republish)
        with self._stat_lock:
            frames = self._frames
        return dict(self._snap, frames=frames)

    # -- scan loop ----------------------------------------------------

    def _run(self) -> None:
        next_dir_scan = 0.0
        idle = 0
        while not self._closing:
            now = time.monotonic()
            if now >= next_dir_scan:
                self._scan_dir()
                next_dir_scan = now + 0.05
            handled = 0
            for path, ring in list(self._rings.items()):
                handled += self._sweep_ring(path, ring)
            self._publish()
            if handled == 0:
                # adaptive nap: right after serving traffic the next
                # frame is usually mid-flight (the client drains and
                # refills within ~0.1ms), so a pass boundary gets a few
                # short naps before falling back to the idle interval —
                # otherwise every pipelined pass pays a full interval
                # stall, which is the difference between beating the
                # UDS lane and trailing it
                idle += 1
                ms = knobs.get_float("LDT_SHM_SCAN_INTERVAL_MS") or 1.0
                time.sleep(ms / 1e3 if idle > 8 else 5e-5)
            else:
                idle = 0

    def _scan_dir(self) -> None:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for name in names:
            if not name.endswith(".ring"):
                continue
            path = os.path.join(self.dir, name)
            if path in self._rings:
                continue
            try:
                mtime = os.stat(path).st_mtime
            except OSError:
                continue
            if self._bad.get(path) == mtime:
                continue
            try:
                self._attach(path)
            except faults.FaultInjected:
                telemetry.REGISTRY.counter_inc(
                    "ldt_shm_reclaimed_total", reason="attach-fault")
                continue       # injected attach failure: retried next
            except (RingError, OSError, ValueError):
                self._bad[path] = mtime
                continue

    def _attach(self, path: str) -> None:
        if faults.ACTIVE is not None:
            faults.hit("shm_attach")
        rf = RingFile(path)
        # generation fence: every attach (first, restart, fleet roll)
        # bumps the ring generation, so frames stamped by the previous
        # worker's era deterministically fail back, never dangle
        gen = rf.generation + 1
        rf.set_generation(gen, os.getpid())
        ring = _WorkerRing(rf)
        ring.generation = gen
        self._rings[path] = ring
        self._bad.pop(path, None)
        flightrec.emit_event("shm_ring_state", state="attached",
                             ring=os.path.basename(path),
                             generation=gen)
        print(json.dumps({"msg": f"shm ring attached: {path} "
                                 f"(generation {gen})"}), flush=True)

    def _detach(self, path: str, ring: _WorkerRing,
                unlink: bool) -> None:
        self._rings.pop(path, None)
        ring.close()
        if unlink:
            flightrec.emit_event("shm_ring_state", state="unlinked",
                                 ring=os.path.basename(path))
            try:
                os.unlink(path)
            except OSError:
                pass

    # -- per-ring sweep -----------------------------------------------

    def _sweep_ring(self, path: str, ring: _WorkerRing) -> int:
        rf = ring.rf
        gen = ring.generation
        timeout = lease_timeout_sec()
        client_alive = _pid_alive(rf.client_pid)
        handled = 0
        free = 0
        for i in range(rf.nslots):
            raw, sgen, pid, ts, length, _status = rf.read_slot(i)
            s = ring.mirrors[i]
            if raw not in SLOT_STATE_NAMES or \
                    not _advance_mirror(s, raw):
                # corrupt header: no legal transition path explains the
                # observed state — repair to FREE
                if not self._reclaim(rf, s, i, "corrupt"):
                    continue
                free += 1
                continue
            if s.state == SLOT_READY:
                if sgen != gen:
                    self._fail_frame(ring, i, "fenced")
                elif length > rf.slot_bytes:
                    self._fail_frame(ring, i, "oversize")
                elif self._lease(ring, i, length):
                    self._complete(ring, i, length)
                    handled += 1
            elif s.state == SLOT_LEASED:
                if sgen != gen:
                    # a previous worker crashed mid-lease: fail the
                    # frame back with an explicit error frame
                    self._fail_frame(ring, i, "fenced")
            elif s.state == SLOT_WRITING:
                stale = time.time() - ts > timeout
                if not _pid_alive(pid) or stale:
                    self._reclaim(rf, s, i, "writer-lost")
            elif s.state == SLOT_DONE:
                if not client_alive and \
                        time.time() - ts > timeout:
                    self._reclaim(rf, s, i, "client-dead")
            if s.state == SLOT_FREE:
                free += 1
        if not client_alive and free == rf.nslots:
            # every frame resolved and the producer is gone: the ring
            # file has no owner left — drop it
            self._detach(path, ring, unlink=True)
        return handled

    def _reclaim(self, rf: RingFile, s: RingSlot, i: int,
                 reason: str) -> bool:
        try:
            if faults.ACTIVE is not None:
                faults.hit("shm_reclaim")
        except faults.FaultInjected:
            return False       # injected reclaim failure: retried next
        _force_free(s)
        rf.write_slot(i, SLOT_FREE, 0, 0, 0.0, 0, 0)
        telemetry.REGISTRY.counter_inc("ldt_shm_reclaimed_total",
                                       reason=reason)
        return True

    def _fail_frame(self, ring: _WorkerRing, i: int,
                    reason: str) -> None:
        """Explicit error frame for a frame that can never score
        (stale generation, oversize length): DONE with a 503/413 so the
        waiting client resolves instead of hanging."""
        body = FENCED_BODY if reason == "fenced" else wire.OVERSIZE_BODY
        status = 503 if reason == "fenced" else 413
        rf = ring.rf
        s = ring.mirrors[i]
        reqid = rf.slot_request_id(i)  # error frames echo the id too
        s.mark_failed()
        rf.write_payload(i, (body,))
        rf.write_slot(i, SLOT_DONE, ring.generation, os.getpid(),
                      time.time(), len(body), status, reqid=reqid)
        telemetry.REGISTRY.counter_inc("ldt_shm_frames_total",
                                       result="fenced")
        telemetry.REGISTRY.counter_inc("ldt_shm_reclaimed_total",
                                       reason="generation")

    def _lease(self, ring: _WorkerRing, i: int, length: int) -> bool:
        """Lease one READY frame: the fault seam and the FSM edge."""
        try:
            if faults.ACTIVE is not None:
                faults.hit("shm_lease")
        except faults.FaultInjected:
            return False       # lease fault: frame stays READY, retried
        ring.mirrors[i].mark_leased()
        ring.rf.write_slot(i, SLOT_LEASED, ring.generation, os.getpid(),
                           time.time(), length, 0)
        return True

    def _complete(self, ring: _WorkerRing, i: int, length: int) -> None:
        """Score one leased frame and publish its response.
        Zero-copy frame feed: the slot's own mmap IS the request
        buffer — the wire fast scanner decodes doc strings straight
        off it, then the response overwrites the same payload region.
        Every exit path writes a DONE header, so the client's wait()
        always resolves."""
        rf = ring.rf
        s = ring.mirrors[i]
        reqid = rf.slot_request_id(i)
        if knobs.get_bool("LDT_WIRE_CRC"):
            if faults.ACTIVE is not None and length:
                # chaos seam: seeded single-bit flip in the shared
                # payload — exactly the corruption the guard word
                # must catch before the frame reaches the parser
                seed = faults.corruption("frame_payload")
                if seed is not None:
                    off = rf.payload_off(i)
                    rng = random.Random(seed)
                    b = rng.randrange(length)
                    rf.mm[off + b] ^= 1 << rng.randrange(8)
            ok = zlib.crc32(rf.read_payload(i, length)) \
                == rf.read_crc(i)
            telemetry.REGISTRY.counter_inc(
                "ldt_integrity_crc_total", lane="shm",
                result="ok" if ok else "mismatch")
            if not ok:
                telemetry.REGISTRY.counter_inc(
                    "ldt_integrity_detected_total",
                    kind="frame_crc", lane="shm")
                body = wire.CRC_ERROR_BODY
                s.mark_done()
                rf.write_payload(i, (body,))
                rf.write_slot(i, SLOT_DONE, ring.generation,
                              os.getpid(), time.time(), len(body),
                              400, reqid=reqid)
                telemetry.REGISTRY.counter_inc(
                    "ldt_shm_frames_total", result="error")
                return
        try:
            status, buffers = wire.handle_frame(
                self.svc, ring.pmaps[i], detect=self._detect,
                nbytes=length, lane="shm",
                request_id=("%08x" % reqid) if reqid else None)
        except Exception as e:  # noqa: BLE001 - typed 500, never a hang
            print(json.dumps({"msg": "shm frame failed",
                              "error": repr(e)}), flush=True)
            status, buffers = 500, [b'{"error":"internal error"}']
        # join before the mmap store: post_detect returns one chunk per
        # doc, and N small slice-assigns into the mapping cost far more
        # than one join + one store (the UDS lane pays one writev)
        resp = buffers[0] if len(buffers) == 1 else b"".join(buffers)
        blen = len(resp)
        if blen > rf.slot_bytes:
            resp, status = RESP_OVERFLOW_BODY, 500
            blen = len(resp)
        rf.write_payload(i, (resp,))
        s.mark_done()
        rf.write_slot(i, SLOT_DONE, ring.generation, os.getpid(),
                      time.time(), blen, status, reqid=reqid)
        with self._stat_lock:
            self._frames += 1
        telemetry.REGISTRY.counter_inc(
            "ldt_shm_frames_total",
            result="ok" if status < 400 else "error")

    def _publish(self) -> None:
        total = 0
        free = 0
        for ring in self._rings.values():
            total += ring.rf.nslots
            free += sum(1 for m in ring.mirrors
                        if m.state == SLOT_FREE)
        with self._stat_lock:
            frames = self._frames
        self._snap = {"rings": len(self._rings), "slots_total": total,
                      "slots_free": free, "frames": frames}
