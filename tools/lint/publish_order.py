"""Publish-order analyzer: the commit word is the LAST store.

Every crash-safe record in this repo publishes by store order: payload
and header tail land in the shared buffer first, then one aligned
4-byte commit/seq/state word makes the record visible. The seqlock
variants (sharedcache) bracket the field stores with an odd claim and
an even publish. Readers must re-validate that word before trusting
payload bytes. docs/ROBUSTNESS.md states this; nothing enforced it.

For each registry layout that declares a commit word
(tools/lint/layout_registry.py), this analyzer runs a flow-sensitive
pass over the declared ``pub_writers``: it linearizes every store into
the mmap buffer (slice/index assignment on an ``mm``-named target, or
``X.pack_into(mm, ...)``) in source order and proves

  * the final buffer store is the commit-word store (flagging
    write-after-commit and commit-before-payload), and
  * seqlock layouts store the commit word at least twice, first
    (the odd claim) and last (the even publish), with every field
    store in between.

and over the declared ``guard_readers`` that they bind a value from
the commit word (a layout/commit-struct unpack, or a declared
``read_helpers`` call like ``sharedcache._seq``) and branch on it
before using payload bytes. All findings share one rule id:

  publish-order    messages distinguish write-after-commit,
                   commit-before-payload, the missing odd->fields->even
                   seqlock sequence, and readers that skip revalidation

A commit-word store is recognized as a 4-byte slice at the record base
(``mm[off:off + 4]`` / ``mm[off:off + COMMIT.size]``) or a
``pack_into`` through the layout's declared ``commit_struct``.
"""
from __future__ import annotations

import ast
from pathlib import Path

from .base import Violation, apply_suppressions, load_source, repo_root
from .layout_registry import LAYOUTS, SCAN_FILES

_UNPACK_METHODS = frozenset({"unpack", "unpack_from", "iter_unpack"})


def _is_mm_name(node: ast.expr) -> bool:
    """The buffer expression every protocol module stores through:
    a bare ``mm`` local or a ``*.mm`` / ``*._mm`` attribute chain."""
    if isinstance(node, ast.Name):
        return node.id in ("mm", "_mm")
    if isinstance(node, ast.Attribute):
        return node.attr in ("mm", "_mm")
    return False


def _commit_width_ok(node: ast.expr, lay) -> bool:
    """Is this slice-width expression exactly the 4-byte commit word?
    Literal 4, or ``X.size`` where X is the layout's own var and that
    layout is 4 bytes wide (capture's COMMIT)."""
    if isinstance(node, ast.Constant) and node.value == 4:
        return True
    return (isinstance(node, ast.Attribute) and node.attr == "size"
            and isinstance(node.value, ast.Name)
            and node.value.id == (lay.var or "")
            and lay.size == 4)


def _is_commit_slice(sub: ast.Subscript, lay) -> bool:
    """mm[L : L + 4] — a 4-byte store at the record base."""
    sl = sub.slice
    if not isinstance(sl, ast.Slice) or sl.lower is None \
            or sl.upper is None or sl.step is not None:
        return False
    up = sl.upper
    return (isinstance(up, ast.BinOp) and isinstance(up.op, ast.Add)
            and ast.dump(up.left) == ast.dump(sl.lower)
            and _commit_width_ok(up.right, lay))


class _StoreScan(ast.NodeVisitor):
    """Ordered buffer-store events of one writer function. AST child
    order is source order, so a depth-first walk linearizes the stores
    exactly as the CPU issues them on the straight-line publish path."""

    def __init__(self, lay):
        self.lay = lay
        self.events: list = []   # ("commit" | "field", lineno)

    def _record_target(self, tgt, lineno):
        if isinstance(tgt, ast.Subscript) and _is_mm_name(tgt.value):
            kind = "commit" if self.lay.commit_slice and \
                _is_commit_slice(tgt, self.lay) else "field"
            self.events.append((kind, lineno))

    def visit_Assign(self, node):
        for tgt in node.targets:
            self._record_target(tgt, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._record_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "pack_into":
            # VAR.pack_into(mm, off, ...) or struct.pack_into(fmt, mm,.)
            buf_idx = 1 if isinstance(f.value, ast.Name) \
                and f.value.id == "struct" else 0
            if len(node.args) > buf_idx \
                    and _is_mm_name(node.args[buf_idx]):
                kind = "field"
                if self.lay.commit_struct \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == self.lay.commit_struct:
                    kind = "commit"
                self.events.append((kind, node.lineno))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        pass  # nested defs are not part of the straight-line store path

    visit_AsyncFunctionDef = visit_FunctionDef


def _find_fn(tree: ast.Module, qual: str):
    """Resolve 'Class.method' / 'function' to its def node."""
    parts = qual.split(".")
    scope: list = tree.body
    node = None
    for i, name in enumerate(parts):
        node = next(
            (n for n in scope if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef,
                    ast.ClassDef)) and n.name == name), None)
        if node is None:
            return None
        scope = node.body
    return node if isinstance(
        node, (ast.FunctionDef, ast.AsyncFunctionDef)) else None


def _check_writer(sf, lay, qual: str, out: list):
    fn = _find_fn(sf.tree, qual)
    if fn is None:
        out.append(Violation(
            "publish-order", sf.rel, 1,
            f"layout {lay.name!r}: declared pub_writer {qual} does "
            f"not exist — update tools/lint/layout_registry.py"))
        return
    scan = _StoreScan(lay)
    for stmt in fn.body:
        scan.visit(stmt)
    events = scan.events
    commits = [i for i, (k, _) in enumerate(events) if k == "commit"]
    if not commits:
        out.append(Violation(
            "publish-order", sf.rel, fn.lineno,
            f"layout {lay.name!r}: writer {qual} never stores the "
            f"commit word — records it writes are unpublishable or "
            f"unconditionally trusted"))
        return
    if lay.seqlock:
        bad = len(commits) < 2 or commits[0] != 0 \
            or commits[-1] != len(events) - 1
        if bad:
            out.append(Violation(
                "publish-order", sf.rel, events[commits[-1]][1],
                f"layout {lay.name!r}: writer {qual} breaks the "
                f"seqlock sequence — stores must go odd claim -> "
                f"fields/payload -> even publish, with the seq word "
                f"first and last"))
        return
    if commits[-1] != len(events) - 1:
        line = events[commits[-1] + 1][1]
        if commits[-1] < min(i for i, (k, _) in enumerate(events)
                             if k == "field"):
            out.append(Violation(
                "publish-order", sf.rel, line,
                f"layout {lay.name!r}: writer {qual} publishes the "
                f"commit word BEFORE the payload/header stores "
                f"(commit-before-payload) — a reader of a crashed "
                f"writer would trust a torn record"))
        else:
            out.append(Violation(
                "publish-order", sf.rel, line,
                f"layout {lay.name!r}: writer {qual} stores into the "
                f"record AFTER the commit-word publication "
                f"(write-after-commit) — the store order is the only "
                f"thing standing between a SIGKILL and a torn record"))


class _GuardScan(ast.NodeVisitor):
    """Commit-word bindings and condition references in one reader."""

    def __init__(self, lay):
        self.lay = lay
        self.commit_names: set = set()
        self.guarded = False

    def _bind(self, target):
        if isinstance(target, ast.Name):
            self.commit_names.add(target.id)
        elif isinstance(target, ast.Tuple) and target.elts \
                and isinstance(target.elts[0], ast.Name):
            # the commit/seq/state word is field 0 of every commit
            # layout, so the first unpacked name is the guard value
            self.commit_names.add(target.elts[0].id)

    def visit_Assign(self, node):
        v = node.value
        if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute):
            attr = v.func.attr
            base = v.func.value
            is_unpack = attr in _UNPACK_METHODS and (
                isinstance(base, ast.Name)
                and base.id in (self.lay.var, self.lay.commit_struct))
            is_helper = attr in self.lay.read_helpers
            if is_unpack or is_helper:
                for tgt in node.targets:
                    self._bind(tgt)
        self.generic_visit(node)

    def _check_test(self, test):
        for n in ast.walk(test):
            if isinstance(n, ast.Name) and n.id in self.commit_names:
                self.guarded = True

    def visit_If(self, node):
        self._check_test(node.test)
        self.generic_visit(node)

    def visit_While(self, node):
        self._check_test(node.test)
        self.generic_visit(node)

    def visit_IfExp(self, node):
        self._check_test(node.test)
        self.generic_visit(node)

    def visit_Assert(self, node):
        self._check_test(node.test)
        self.generic_visit(node)


def _check_reader(sf, lay, qual: str, out: list):
    fn = _find_fn(sf.tree, qual)
    if fn is None:
        out.append(Violation(
            "publish-order", sf.rel, 1,
            f"layout {lay.name!r}: declared guard_reader {qual} does "
            f"not exist — update tools/lint/layout_registry.py"))
        return
    scan = _GuardScan(lay)
    scan.visit(fn)
    if not scan.commit_names:
        out.append(Violation(
            "publish-order", sf.rel, fn.lineno,
            f"layout {lay.name!r}: reader {qual} never reads the "
            f"commit word (no {lay.var or lay.commit_struct} unpack "
            f"or {'/'.join(lay.read_helpers) or 'helper'} call)"))
        return
    if not scan.guarded:
        out.append(Violation(
            "publish-order", sf.rel, fn.lineno,
            f"layout {lay.name!r}: reader {qual} does not re-validate "
            f"the commit/seq word before trusting payload bytes — "
            f"torn records of a crashed writer would be accepted"))


def check(root: Path | None = None, files=None, layouts=LAYOUTS):
    """Run the analyzer. Returns (violations, n_suppressed)."""
    root = root or repo_root()
    scope = set(SCAN_FILES) if files is None else set(files)
    violations: list = []
    n_suppressed = 0
    by_file: dict = {}
    for lay in layouts:
        if not lay.commit:
            continue
        for qual_list, checker in ((lay.pub_writers, _check_writer),
                                   (lay.guard_readers, _check_reader)):
            for entry in qual_list:
                rel, _, qual = entry.partition("::")
                if rel not in scope:
                    continue
                by_file.setdefault(rel, []).append(
                    (lay, qual, checker))
    for rel in sorted(by_file):
        path = root / rel
        if not path.exists():
            continue
        sf = load_source(path, root)
        file_violations: list = []
        for lay, qual, checker in by_file[rel]:
            checker(sf, lay, qual, file_violations)
        kept, ns = apply_suppressions(sf, file_violations)
        violations.extend(kept)
        n_suppressed += ns
    return violations, n_suppressed
