"""Native (C++) host runtime: the batch packer.

Loads libldtpack.so (built on demand from packer.cc) and exposes
`pack_batch_native`, an array-for-array drop-in for the Python
preprocess.pack.pack_batch (tests/test_native_pack.py asserts equality).
Falls back gracefully: `available()` is False when no compiler/library
exists and callers keep using the Python packer.
"""
from __future__ import annotations

import ctypes
import dataclasses
import subprocess
from pathlib import Path

import numpy as np

from ..registry import Registry, ULSCRIPT_LATIN
from ..tables import ScoringTables
from ..preprocess.pack import PackedBatch

_DIR = Path(__file__).parent
_SO = _DIR / "libldtpack.so"

_lib = None
_init_keepalive: list = []
_lock = __import__("threading").Lock()


def _build() -> bool:
    try:
        subprocess.run([str(_DIR / "build.sh")], check=True,
                       capture_output=True, timeout=120)
        return _SO.exists()
    except Exception:
        return False


_SYMBOLS = ("ldt_init", "ldt_pack_batch", "ldt_init_tables",
            "ldt_pack_flat_begin", "ldt_pack_flat_finish",
            "ldt_pack_flat_free", "ldt_epilogue_flat", "ldt_init_detect",
            "detect_language", "detect_language_n",
            "ldt_detect_one_full", "ldt_detect_batch_codes")
_ABI_VERSION = 10  # must match packer.cc ldt_abi_version()


def _try_load_all():
    """CDLL + symbol & ABI-version check; None for a missing or stale .so
    (older source set OR older ABI — signature/wire-layout changes bump
    _ABI_VERSION so a cached binary can never silently corrupt results)."""
    try:
        lib = ctypes.CDLL(str(_SO))
        lib.ldt_abi_version.restype = ctypes.c_int32
        if lib.ldt_abi_version() != _ABI_VERSION:
            return None
        for sym in _SYMBOLS:
            getattr(lib, sym).restype = None
        lib.ldt_pack_flat_begin.restype = ctypes.c_int64
        lib.detect_language.restype = ctypes.c_char_p
        lib.detect_language.argtypes = [ctypes.c_char_p]
        lib.detect_language_n.restype = ctypes.c_char_p
        lib.detect_language_n.argtypes = [ctypes.c_char_p,
                                          ctypes.c_int32]
        lib.ldt_detect_one_full.restype = ctypes.c_int32
        return lib
    except (OSError, AttributeError):
        return None


def _host_isa() -> str:
    """Fingerprint of this host's instruction set (build.sh writes the
    builder's into the .host sidecar). A mismatch means the cached .so
    was -march=native-compiled on different hardware — loading it risks
    SIGILL, so the loader rebuilds instead. md5 here is a checksum, not
    crypto (it matches build.sh's md5sum) — declared as such so FIPS
    OpenSSL builds allow it; where even that raises (md5 compiled out
    entirely), a constant that can never match any md5sum sidecar makes
    the loader rebuild once instead of crashing every native load."""
    import hashlib
    import platform
    flags = b""
    try:
        for line in Path("/proc/cpuinfo").read_bytes().splitlines():
            if line.startswith(b"flags"):
                flags = line + b"\n"  # grep emits the trailing newline
                break
    except OSError:
        pass
    try:
        digest = hashlib.md5(flags, usedforsecurity=False).hexdigest()
    except ValueError:
        digest = "md5-unavailable"
    return f"{platform.machine()}\n{digest}  -\n"


def _sidecar_ok(so: Path) -> bool:
    """ISA check for one .so via its .host sidecar. A MISSING or
    unreadable sidecar next to an existing .so means "ISA unknown, load
    anyway": read-only installs (containers, wheels) can never write
    sidecars, and rebuild-once-per-check would turn into
    rebuild-every-process there. Only a sidecar that EXISTS and
    disagrees forces a rebuild."""
    sidecar = so.with_suffix(".so.host")
    try:
        if not sidecar.exists():
            return True
        return sidecar.read_text() == _host_isa()
    except OSError:
        return True  # unreadable: treat as unknown, load anyway


def _isa_matches() -> bool:
    return _sidecar_ok(_SO)


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        lib = _try_load_all() if _SO.exists() and _isa_matches() else None
        if lib is None:
            # missing or stale: rebuild once, then retry
            try:
                _SO.unlink(missing_ok=True)
            except OSError:
                pass
            lib = _try_load_all() if _build() else None
        _lib = lib if lib is not None else False
        return _lib


def available() -> bool:
    return bool(_load())


_initialized_for: tuple = ()

_glue = None
_GLUE_VERSION = 1  # must match pyglue.c ldt_glue_version()


def _try_load_glue(so: Path):
    """Load + contract-check the glue; None when unusable."""
    try:
        g = ctypes.PyDLL(str(so))
        g.ldt_glue_version.restype = ctypes.c_int64
        if g.ldt_glue_version() != _GLUE_VERSION:
            return None
        g.ldt_blob_from_list.restype = ctypes.c_int64
        g.ldt_blob_from_list.argtypes = [
            ctypes.py_object, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_void_p]
        g.ldt_blob_size.restype = ctypes.c_int64
        g.ldt_blob_size.argtypes = [ctypes.py_object]
        return g
    except (OSError, AttributeError):
        return None


def _load_glue():
    """Optional GIL-held marshalling helper (libldtglue.so, built by
    build.sh when CPython headers exist). ctypes.PyDLL: the GIL stays
    held across calls — every function inside touches CPython API.

    A unusable binary (missing, older than its source, wrong contract
    version, or foreign-ISA sidecar) triggers ONE glue-only rebuild —
    never the full build, which would rewrite the already-dlopen'd
    libldtpack.so in place — and only where CPython headers exist (a
    host without them must not recompile the packer per process).
    Failure after that caches False: Python marshalling path."""
    global _glue
    if _glue is not None:
        return _glue or None
    with _lock:
        if _glue is not None:
            return _glue or None
        so = _DIR / "libldtglue.so"
        try:
            fresh = (so.exists()
                     and so.stat().st_mtime >=
                     (_DIR / "pyglue.c").stat().st_mtime
                     and _sidecar_ok(so))
        except OSError:
            fresh = False
        g = _try_load_glue(so) if fresh else None
        if g is None:
            import os
            import sysconfig
            incdir = sysconfig.get_paths()["include"]
            if (Path(incdir) / "Python.h").exists():
                try:
                    subprocess.run(
                        [str(_DIR / "build.sh"), "--glue-only"],
                        check=True, capture_output=True, timeout=120,
                        env={**os.environ, "LDT_PYINC": incdir})  # ldt-lint: disable=knob-direct-env -- whole-environment passthrough to the build subprocess, not a config read
                    # re-verify freshness: build.sh exits 0 even when
                    # it could not compile, and loading the stale
                    # binary the check above just rejected would
                    # bypass the mtime/ISA protection entirely
                    if (so.exists()
                            and so.stat().st_mtime >=
                            (_DIR / "pyglue.c").stat().st_mtime
                            and _sidecar_ok(so)):
                        g = _try_load_glue(so)
                except Exception:  # noqa: BLE001 - fall back quietly
                    g = None
        _glue = g if g is not None else False
        return _glue or None


def _marshal_texts(texts: list):
    """list[str] -> (utf-8 blob u8 ndarray, bounds i64 ndarray). The C
    glue path is one encode + one memcpy with no per-doc bytes objects
    (~6ms/16K docs saved on the single-core host); the Python path
    handles everything else — non-list inputs, lone surrogates (encoded
    surrogatepass, exactly as before), or a missing glue .so.

    Memory trade-off, deliberate: PyUnicode_AsUTF8AndSize caches each
    non-ASCII str's UTF-8 form ON the str for its lifetime. Service
    texts are request-scoped (cache freed with them); a caller that
    detects a long-lived in-memory corpus pays ~2x its non-ASCII text
    RSS — such callers can pre-encode and use the bytes-based eval
    harness instead."""
    B = len(texts)
    g = _load_glue()
    if g is not None and type(texts) is list:
        bounds = np.empty(B + 1, np.int64)
        total = g.ldt_blob_size(ctypes.py_object(texts))
        if total >= 0:
            blob = np.empty(max(int(total), 1), np.uint8)
            r = g.ldt_blob_from_list(ctypes.py_object(texts),
                                     ctypes.c_int64(B),
                                     _ptr(blob, np.uint8),
                                     ctypes.c_int64(blob.nbytes),
                                     _ptr(bounds, np.int64))
            if r == total:
                return blob, bounds
    enc = [t.encode("utf-8", errors="surrogatepass") for t in texts]
    bounds = np.zeros(B + 1, np.int64)
    np.cumsum([len(e) for e in enc], out=bounds[1:])
    blob = np.frombuffer(b"".join(enc), dtype=np.uint8) if bounds[-1] \
        else np.zeros(1, np.uint8)
    return np.ascontiguousarray(blob), bounds


def _ptr(a: np.ndarray, dtype):
    assert a.dtype == dtype and a.flags.c_contiguous
    return a.ctypes.data_as(ctypes.c_void_p)


def _ensure_init(tables: ScoringTables, reg: Registry):
    """Upload table pointers once per (tables, registry) pair. Holds
    strong references to the actual objects (not ids — CPython recycles
    addresses) and serializes re-init across threads."""
    global _initialized_for
    key = (tables, reg)
    if _initialized_for and _initialized_for[0] is tables and \
            _initialized_for[1] is reg:
        return
    lib = _load()
    with _lock:
        if _initialized_for and _initialized_for[0] is tables and \
                _initialized_for[1] is reg:
            return
        seed_lp = np.zeros(reg.num_scripts, np.uint32)
        for s in range(reg.num_scripts):
            lang = reg.default_language(s)
            seed_lp[s] = np.uint32(
                reg.per_script_number(ULSCRIPT_LATIN, lang) << 8)
        rtype = np.ascontiguousarray(reg.ulscript_rtype.astype(np.int32))
        deflang = np.ascontiguousarray(
            reg.ulscript_default_lang.astype(np.int32))
        script_of = np.ascontiguousarray(tables.script_of_cp, dtype=np.uint8)
        lower = np.arange(0x110000, dtype=np.uint32)
        lower[tables.lower_pairs[:, 0]] = tables.lower_pairs[:, 1]
        cjk_prop = np.ascontiguousarray(tables.cjk_uni_prop, dtype=np.uint8)
        _init_keepalive.clear()
        _init_keepalive.extend([seed_lp, rtype, deflang, script_of, lower,
                                cjk_prop])
        lib.ldt_init(
            _ptr(script_of, np.uint8), _ptr(lower, np.uint32),
            _ptr(cjk_prop, np.uint8), _ptr(rtype, np.int32),
            _ptr(deflang, np.int32), _ptr(seed_lp, np.uint32),
            ctypes.c_int32(reg.num_scripts),
            ctypes.c_int32(1 if tables.distinctbi.empty else 0))
        # host resolution tables (packer.cc resolve path); HostTables is
        # cached per (tables, reg) so the pointers stay alive with it
        from ..ops.device_tables import host_tables
        ht = host_tables(tables, reg)
        _init_keepalive.append(ht)
        # scoring indices and the per-script seeds must stay below the
        # hint-boost window, or wire idx values would alias into it
        if len(ht.cat_ind) + reg.num_scripts > HINT_BASE:
            raise RuntimeError(
                f"scoring tables too large for the u16 wire: "
                f"{len(ht.cat_ind)} + {reg.num_scripts} seed rows "
                f"reach the hint window at {HINT_BASE}")
        lib.ldt_init_tables(
            _ptr(ht.cat_buckets, np.uint32), _ptr(ht.cat_ind2, np.uint32),
            ctypes.c_int64(len(ht.cat_ind)),
            _ptr(ht.bucket_off, np.int64), _ptr(ht.size, np.uint32),
            _ptr(ht.keymask, np.uint32), _ptr(ht.ind_off, np.int32),
            _ptr(ht.size_one, np.int32), _ptr(ht.probes, np.uint8),
            ctypes.c_int64(ht.q2.bucket_off),
            ctypes.c_uint32(ht.q2.size), ctypes.c_uint32(ht.q2.keymask),
            ctypes.c_int32(ht.q2.ind_off), ctypes.c_int32(ht.q2.size_one),
            ctypes.c_int32(1 if ht.q2_enabled else 0),
            ctypes.c_int32(ht.seed_ind_base))
        # C ABI detection path (wrapper.h:8 seam): scoring + epilogue
        # tables so detect_language()/ldt_detect_batch_codes() run with
        # no Python in the loop
        lg3 = np.zeros((256, 3), np.uint8)
        lg3[:tables.lg_prob.shape[0]] = tables.lg_prob[:, 5:8]
        plang = np.ascontiguousarray(np.stack([
            reg.plang_to_lang_latn.astype(np.int32),
            reg.plang_to_lang_othr.astype(np.int32)]))
        n = reg.num_languages
        expected = np.zeros((n, 4), np.int32)
        es = tables.avg_delta_octa_score.astype(np.int32).reshape(-1, 4)
        expected[:min(n, es.shape[0])] = es[:n]
        close, alt, figs = _epilogue_reg_arrays(reg)
        stride = 8
        codes = np.zeros(n * stride, np.uint8)
        for lang in range(n):
            b = str(reg.lang_code[lang]).encode()[:stride - 1]
            codes[lang * stride:lang * stride + len(b)] = \
                np.frombuffer(b, np.uint8)
        _init_keepalive.extend([lg3, plang, expected, close, alt, figs,
                                codes])
        lib.ldt_init_detect(
            _ptr(lg3, np.uint8), _ptr(plang, np.int32),
            _ptr(expected, np.int32), _ptr(close, np.int32),
            _ptr(alt, np.int32), _ptr(figs, np.uint8),
            ctypes.c_int32(n),
            codes.ctypes.data_as(ctypes.c_char_p),
            ctypes.c_int32(stride))
        _initialized_for = key


def ensure_init(tables: ScoringTables, reg: Registry):
    """Public init seam for C-ABI hosts and tests: upload every table the
    native library needs (packing + the C-only detection path), exactly
    as the batched engine's first pack would."""
    lib = _load()
    if not lib:
        raise RuntimeError("native library unavailable")
    _ensure_init(tables, reg)
    return lib


def pack_batch_native(texts: list[str], tables: ScoringTables,
                      reg: Registry, max_slots: int = 2048,
                      max_chunks: int = 64, max_direct: int = 4,
                      flags: int = 0, n_threads: int = 0) -> PackedBatch:
    """Native twin of preprocess.pack.pack_batch (same output contract)."""
    lib = _load()
    if not lib:
        raise RuntimeError("native packer unavailable")
    _ensure_init(tables, reg)

    B, L, C, D = len(texts), max_slots, max_chunks, max_direct
    blob, bounds = _marshal_texts(texts)

    out = PackedBatch(
        kind=np.zeros((B, L), np.int8),
        offset=np.zeros((B, L), np.int32),
        fp=np.zeros((B, L), np.uint32),
        fp_hi=np.zeros((B, L), np.uint8),
        chunk_base=np.zeros((B, L), np.int32),
        span_start=np.zeros((B, L), np.int32),
        span_end_off=np.zeros((B, L), np.int32),
        side=np.zeros((B, L), np.int8),
        cjk=np.zeros((B, L), np.int8),
        script=np.zeros((B, L), np.int16),
        chunk_script=np.zeros((B, C), np.int16),
        chunk_cjk=np.zeros((B, C), np.int8),
        chunk_side=np.zeros((B, C), np.int8),
        chunk_span_end=np.zeros((B, C), np.int32),
        direct_adds=np.full((B, D, 3), -1, np.int32),
        text_bytes=np.zeros(B, np.int32),
        fallback=np.zeros(B, bool),
        n_slots=np.zeros(B, np.int32),
        n_chunks=np.zeros(B, np.int32),
        n_docs=B,
    )
    if n_threads <= 0:
        import os
        n_threads = min(8, os.cpu_count() or 1)
    lib.ldt_pack_batch(
        _ptr(blob, np.uint8), _ptr(bounds, np.int64),
        ctypes.c_int32(B), ctypes.c_int32(L), ctypes.c_int32(C),
        ctypes.c_int32(D), ctypes.c_int32(flags),
        ctypes.c_int32(n_threads),
        _ptr(out.kind, np.int8), _ptr(out.offset, np.int32),
        _ptr(out.fp, np.uint32), _ptr(out.fp_hi, np.uint8),
        _ptr(out.chunk_base, np.int32), _ptr(out.span_start, np.int32),
        _ptr(out.span_end_off, np.int32), _ptr(out.side, np.int8),
        _ptr(out.cjk, np.int8), _ptr(out.script, np.int16),
        _ptr(out.chunk_script, np.int16), _ptr(out.chunk_cjk, np.int8),
        _ptr(out.chunk_side, np.int8), _ptr(out.chunk_span_end, np.int32),
        out.direct_adds.ctypes.data_as(ctypes.c_void_p),
        _ptr(out.text_bytes, np.int32),
        out.fallback.ctypes.data_as(ctypes.c_void_p),
        _ptr(out.n_slots, np.int32), _ptr(out.n_chunks, np.int32))
    return out


# -- chunk-major flat wire (packer.cc ldt_pack_flat_begin/finish) -----------


@dataclasses.dataclass
class ChunkBatch:
    """Chunk-major flat wire + the per-doc host arrays the epilogue needs.

    The wire has NO document axis: all docs' resolved slots concatenate
    into idx, chunks are rows addressed by (cstart, cnsl), and the device
    program shape depends only on content volume (N slots, Gs chunks per
    shard, K = fattest chunk) — never on batch size or document length.
    """
    wire: dict               # idx [D,N] u16; cnsl [D,Gs] u8 (chunk
                             # starts derive on device by cumsum);
                             # cmeta [D,Gs] u32; cscript [D,Gs] u8;
                             # cwhack [D,Gs] u16 or [D,1] dummy when no
                             # doc carries whacks; k_iota [K] u8
    doc_chunk_start: np.ndarray  # [B] i64 first chunk row in flat [D*Gs]
    direct_adds: np.ndarray  # [B, Dcap, 3] i32
    text_bytes: np.ndarray   # [B] i32
    fallback: np.ndarray     # [B] bool
    squeezed: np.ndarray     # [B] bool
    n_slots: np.ndarray      # [B] i32 (0 for fallback docs)
    n_chunks: np.ndarray     # [B] i32
    n_docs: int = 0
    # want_ranges packs only — host-side result-vector sidecars, never
    # shipped to the device: soff/sorig [D,N] i32 per-slot span/original
    # offsets (-1 = boost/hint slot), clo/chi [D,Gs] i32 chunk ranges in
    # original bytes, crid [D,Gs] i32 hit-round ids (-1 = direct-add),
    # cdir [D,Gs] u8 direct-add flags
    ranges: dict | None = None
    # staging-ring lease backing the wire's bucketed arrays (None when
    # the pack allocated fresh arrays). The OWNER of the dispatch calls
    # release() once no launch can read the wire again — after the
    # result future resolves (direct path) or the pool future settles
    # (pooled path: hedges/failovers may re-read the wire until then).
    staging: "StagingLease | None" = None

    def release_staging(self) -> None:
        if self.staging is not None:
            self.staging.release()
            self.staging = None


class StagingLease:
    """One checked-out set of staging arrays; release() returns it to
    its ring exactly once (idempotent, thread-safe via the ring lock)."""

    __slots__ = ("ring", "key", "arrays", "_done")

    def __init__(self, ring: "StagingRing", key: tuple, arrays: dict):
        self.ring = ring
        self.key = key
        self.arrays = arrays
        self._done = False

    def release(self) -> None:
        self.ring._release(self)


class StagingRing:
    """Per-bucket-tier ring of pre-allocated host staging arrays for the
    flat wire's bucketed lanes (idx/cnsl/cmeta/cscript/cwhack).

    The pipelined engine packs batch N+1 while batch N scores; without a
    ring every pack allocates (and the allocator touches) megabytes of
    fresh pages per dispatch. Arrays are keyed by the padded shape
    bucket (D, N, Gs, whacked) — the same small ladder the compile
    cache keys on — so steady state allocates nothing: acquire() hands
    back a zeroed lease from the free list, the pack writes it, the
    dispatch reads it, and the engine releases it once the result
    future settles. Over-depth demand (ring empty) falls back to a
    fresh allocation that joins the ring on release, up to `cap` sets
    per shape; beyond that the arrays are simply dropped.

    JAX copies host numpy inputs into device buffers synchronously
    during the jitted call, so a released lease can never alias live
    device memory; the pool's settled accounting guarantees no
    host-side reader (hedge/failover re-dispatch) is left either."""

    _KEYS = ("idx", "cnsl", "cmeta", "cscript", "cwhack")

    def __init__(self, cap: int = 4):
        self.cap = cap
        self._free: dict = {}      # key -> list[dict of arrays]
        self._out = 0              # leases currently checked out
        self._hits = 0             # acquires served from the free list
        self._misses = 0           # acquires that had to allocate
        self._lock = __import__("threading").Lock()

    @staticmethod
    def _alloc(key: tuple) -> dict:
        D, N, Gs, whacked = key
        return dict(idx=np.zeros((D, N), np.uint16),
                    cnsl=np.zeros((D, Gs), np.uint8),
                    cmeta=np.zeros((D, Gs), np.uint32),
                    cscript=np.zeros((D, Gs), np.uint8),
                    cwhack=np.zeros((D, Gs if whacked else 1),
                                    np.uint16))

    def acquire(self, D: int, N: int, Gs: int,
                whacked: bool) -> StagingLease:
        key = (D, N, Gs, whacked)
        with self._lock:
            free = self._free.get(key)
            arrays = free.pop() if free else None
            self._out += 1
            if arrays is not None:
                self._hits += 1
            else:
                self._misses += 1
        if arrays is None:
            arrays = self._alloc(key)
        else:
            for a in arrays.values():
                a.fill(0)  # pack relies on zero-initialized padding
        return StagingLease(self, key, arrays)

    def _release(self, lease: StagingLease) -> None:
        with self._lock:
            if lease._done:
                return
            lease._done = True
            self._out -= 1
            free = self._free.setdefault(lease.key, [])
            if len(free) < self.cap:
                free.append(lease.arrays)

    def stats(self) -> dict:
        with self._lock:
            return {"occupancy": self._out,
                    "hits": self._hits,
                    "misses": self._misses,
                    "shapes": len(self._free)}


def _next_pow2_min(n: int, lo: int) -> int:
    b = lo
    while b < n:
        b <<= 1
    return b


def _bucket_step(n: int, step: int, lo: int) -> int:
    """Shape bucket: powers of two from lo up to step, then multiples of
    step — small batches get small programs, large batches bound padding
    waste to one step, and the compiled program set stays small."""
    n = max(n, 1)
    if n >= step:
        return -(-n // step) * step
    b = lo
    while b < n:
        b <<= 1
    return b


# K buckets: the slot axis of one chunk row. Slot counts concentrate at
# 10-40; the ladder keeps padding compute <= 2x while capping the
# program count at 4 per (N, Gs) shape.
_K_BUCKETS = (32, 64, 128, 256)


# Hint-boost window base: wire idx values >= this address the per-batch
# hint_lp table instead of cat_ind2 (packer.cc kHintBase; scoring tables
# end well below it — validated at init)
HINT_BASE = 40960


def _hint_arrays(hint_boosts, B: int):
    """Per-doc HintBoosts -> (hint_lp table, hint_boost [B,2,4] window
    indices, whack_tbl [W,2,256] masks, doc_whack [B] rows). None when
    no doc carries hints (the common case packs hint-free)."""
    if hint_boosts is None or all(
            hb is None or hb.empty() for hb in hint_boosts):
        return None, None, None, None
    lp_index: dict = {}
    whack_index: dict = {((), ()): 0}  # row 0 = no whacks
    hint_boost = np.full((B, 2, 4), -1, np.int32)
    doc_whack = np.zeros(B, np.int32)
    whack_sets: list = [((), ())]
    for b, hb in enumerate(hint_boosts):
        if hb is None or hb.empty():
            continue
        for side, boosts in ((0, hb.boost_latn), (1, hb.boost_othr)):
            for s, lp in enumerate(list(boosts)[:4]):
                if lp <= 0:
                    continue
                w = lp_index.setdefault(int(lp), len(lp_index))
                hint_boost[b, side, s] = w
        wset = (tuple(sorted({(lp >> 8) & 0xFF
                              for lp in hb.whack_latn if lp > 0})),
                tuple(sorted({(lp >> 8) & 0xFF
                              for lp in hb.whack_othr if lp > 0})))
        if wset != ((), ()):
            row = whack_index.get(wset)
            if row is None:
                row = len(whack_sets)
                whack_index[wset] = row
                whack_sets.append(wset)
            doc_whack[b] = row
    if len(lp_index) > 16384:
        raise ValueError("too many distinct hint langprobs in one batch")
    hint_lp = np.zeros(max(len(lp_index), 1), np.uint32)
    for lp, w in lp_index.items():
        hint_lp[w] = lp
    whack_tbl = np.zeros((len(whack_sets), 2, 256), np.uint8)
    for row, (wl, wo) in enumerate(whack_sets):
        for ps in wl:
            whack_tbl[row, 0, ps] = 1
        for ps in wo:
            whack_tbl[row, 1, ps] = 1
    return hint_lp, hint_boost, whack_tbl, doc_whack


def pack_chunks_native(texts: list[str], tables: ScoringTables,
                       reg: Registry, flags: int = 0, n_shards: int = 1,
                       l_doc: int = 1 << 17, c_doc: int = 1 << 14,
                       max_direct: int = 64, n_threads: int = 0,
                       hint_boosts: list | None = None,
                       hint_priors: list | None = None,
                       want_ranges: bool = False,
                       staging: "StagingRing | None" = None) -> ChunkBatch:
    """texts -> chunk-major flat wire (one dispatch regardless of the
    batch's document-length mix). len(texts) must divide n_shards.
    hint_boosts: optional per-doc hints.HintBoosts (None entries fine) —
    prior boosts ride the wire as extra chunk slots addressing the
    hint_lp window; whacks become per-chunk mask rows.
    hint_priors: optional per-doc [2, 256] u8 prior vectors
    (hints.prior_vector, None entries fine) for the LDT_HINTS=1
    reduction term — deduped into a prior_tbl wire plane plus a
    per-chunk cprior row index. The cprior/prior_tbl keys exist ONLY
    when at least one document carries a prior, so prior-free batches
    trace the identical device program they always did."""
    lib = _load()
    if not lib:
        raise RuntimeError("native packer unavailable")
    _ensure_init(tables, reg)

    B, Dc = len(texts), max_direct
    hint_lp, hint_boost, whack_tbl, doc_whack = _hint_arrays(
        hint_boosts, B)
    assert B % n_shards == 0, (B, n_shards)
    blob, bounds = _marshal_texts(texts)

    direct_adds = np.full((B, Dc, 3), -1, np.int32)
    text_bytes = np.zeros(B, np.int32)
    fallback = np.zeros(B, bool)
    squeezed = np.zeros(B, bool)
    n_slots = np.zeros(B, np.int32)
    n_chunks = np.zeros(B, np.int32)
    max_nsl = ctypes.c_int32(0)
    if n_threads <= 0:
        import os
        # CPU-bound work: one worker per core. Oversubscribing a
        # single-core host costs ~4ms/16K-doc batch in fresh-thread
        # page faults and context switches (workers spawn per batch,
        # so their thread-local scratch never stays warm), while the
        # nt=1 path packs on the calling thread with persistent
        # scratch.
        n_threads = min(16, os.cpu_count() or 1)
    handle = lib.ldt_pack_flat_begin(
        _ptr(blob, np.uint8), _ptr(bounds, np.int64),
        ctypes.c_int32(B), ctypes.c_int32(l_doc), ctypes.c_int32(c_doc),
        ctypes.c_int32(Dc), ctypes.c_int32(flags),
        ctypes.c_int32(n_threads),
        ctypes.c_int32(1 if want_ranges else 0),
        _ptr(hint_boost, np.int32) if hint_boost is not None
        else ctypes.c_void_p(None),
        _ptr(direct_adds, np.int32), _ptr(text_bytes, np.int32),
        fallback.ctypes.data_as(ctypes.c_void_p),
        squeezed.ctypes.data_as(ctypes.c_void_p),
        _ptr(n_slots, np.int32), _ptr(n_chunks, np.int32),
        ctypes.byref(max_nsl))

    lease = None
    try:
        D = n_shards
        shard_slots = n_slots.reshape(D, B // D).sum(axis=1)
        shard_chunks = n_chunks.reshape(D, B // D).sum(axis=1)
        # 32K-slot / 8K-chunk step granularity: padding waste stays
        # bounded while the compiled program set stays small (shapes
        # repeat across batches)
        N = _bucket_step(int(shard_slots.max()), 32768, 4096)
        Gs = _bucket_step(int(shard_chunks.max()), 8192, 512)
        K = next(k for k in _K_BUCKETS if k >= max(int(max_nsl.value), 1))

        if staging is not None:
            lease = staging.acquire(D, N, Gs, doc_whack is not None)
            idx = lease.arrays["idx"]
            cnsl = lease.arrays["cnsl"]
            cmeta = lease.arrays["cmeta"]
            cscript = lease.arrays["cscript"]
            cwhack = lease.arrays["cwhack"]
        else:
            idx = np.zeros((D, N), np.uint16)
            cnsl = np.zeros((D, Gs), np.uint8)
            cmeta = np.zeros((D, Gs), np.uint32)
            cscript = np.zeros((D, Gs), np.uint8)
            # hint-free batches (the overwhelmingly common case) ship a
            # 1-wide dummy whack lane: the scorer skips the whack gather
            # at trace time and ~64KB/batch stays off the wire
            cwhack = np.zeros((D, Gs if doc_whack is not None else 1),
                              np.uint16)
        doc_chunk_start = np.zeros(B, np.int64)
        # hint leaves pad to power-of-two buckets to bound program-count
        # growth with hint-table size. Per (N, Gs, K) shape there are
        # exactly TWO program variants — whack-free (1-wide cwhack
        # dummy, the overwhelmingly common case, 64KB/batch lighter) and
        # whacked — a deliberate trade of one extra compile at a warm
        # shape's first whacked batch for wire off every plain batch
        Hb = _next_pow2_min(len(hint_lp) if hint_lp is not None else 1,
                            32)
        hint_lp_w = np.zeros(Hb, np.uint32)
        if hint_lp is not None:
            hint_lp_w[:len(hint_lp)] = hint_lp
        Wb = _next_pow2_min(
            whack_tbl.shape[0] if whack_tbl is not None else 1, 1)
        whack_w = np.zeros((Wb, 2, 256), np.uint8)
        if whack_tbl is not None:
            whack_w[:whack_tbl.shape[0]] = whack_tbl
        if want_ranges:
            ranges = dict(soff=np.zeros((D, N), np.int32),
                          sorig=np.zeros((D, N), np.int32),
                          clo=np.zeros((D, Gs), np.int32),
                          chi=np.zeros((D, Gs), np.int32),
                          crid=np.zeros((D, Gs), np.int32),
                          cdir=np.zeros((D, Gs), np.uint8))
        else:
            ranges = None
    except BaseException:
        # finish() is the only free-er; without this the C++-owned
        # compacted batch would leak on allocation failure / interrupt
        if lease is not None:
            lease.release()
        lib.ldt_pack_flat_free(ctypes.c_int64(handle))
        raise
    lib.ldt_pack_flat_finish(
        ctypes.c_int64(handle), ctypes.c_int32(B), ctypes.c_int32(D),
        ctypes.c_int32(N), ctypes.c_int32(Gs),
        _ptr(n_slots, np.int32), _ptr(n_chunks, np.int32),
        _ptr(doc_whack, np.int32) if doc_whack is not None
        else ctypes.c_void_p(None),
        _ptr(idx, np.uint16),
        _ptr(cnsl, np.uint8), _ptr(cmeta, np.uint32),
        _ptr(cscript, np.uint8),
        _ptr(cwhack, np.uint16) if doc_whack is not None
        else ctypes.c_void_p(None),
        _ptr(doc_chunk_start, np.int64),
        _ptr(ranges["soff"], np.int32) if ranges is not None
        else ctypes.c_void_p(None),
        _ptr(ranges["sorig"], np.int32) if ranges is not None
        else ctypes.c_void_p(None),
        _ptr(ranges["clo"], np.int32) if ranges is not None
        else ctypes.c_void_p(None),
        _ptr(ranges["chi"], np.int32) if ranges is not None
        else ctypes.c_void_p(None),
        _ptr(ranges["crid"], np.int32) if ranges is not None
        else ctypes.c_void_p(None),
        _ptr(ranges["cdir"], np.uint8) if ranges is not None
        else ctypes.c_void_p(None))
    wire = dict(idx=idx, cnsl=cnsl, cmeta=cmeta,
                cscript=cscript, cwhack=cwhack, hint_lp=hint_lp_w,
                whack_tbl=whack_w, k_iota=np.zeros(K, np.uint8))
    if hint_priors is not None and any(p is not None for p in hint_priors):
        # LDT_HINTS=1 prior term: dedup the per-doc [2, 256] planes into
        # a pow2-padded table (row 0 = the no-prior zero plane) and mark
        # each document's chunks with its row via the flat contiguity
        # invariant. Fresh allocations, not the staging ring — priors
        # ride only the rare hinted lane, so pinning ring capacity for
        # them would tax every plain batch.
        planes: list[bytes] = [bytes(2 * 256)]
        plane_row: dict[bytes, int] = {planes[0]: 0}
        cprior = np.zeros((D, Gs), np.uint16)
        cprior_flat = cprior.reshape(-1)
        for b in range(min(B, len(hint_priors))):
            pv = hint_priors[b]
            if pv is None:
                continue
            key = np.ascontiguousarray(pv, dtype=np.uint8).tobytes()
            row = plane_row.get(key)
            if row is None:
                row = len(planes)
                planes.append(key)
                plane_row[key] = row
            s = int(doc_chunk_start[b])
            cprior_flat[s:s + int(n_chunks[b])] = row
        Pb = _next_pow2_min(len(planes), 1)
        prior_tbl = np.zeros((Pb, 2, 256), np.uint8)
        for row, key in enumerate(planes):
            prior_tbl[row] = np.frombuffer(key, np.uint8).reshape(2, 256)
        wire["cprior"] = cprior
        wire["prior_tbl"] = prior_tbl
    return ChunkBatch(wire=wire, doc_chunk_start=doc_chunk_start,
                      direct_adds=direct_adds, text_bytes=text_bytes,
                      fallback=fallback, squeezed=squeezed,
                      n_slots=n_slots, n_chunks=n_chunks, n_docs=B,
                      ranges=ranges, staging=lease)


# Reference 160KB-per-document scoring subset (packer.cc
# kCabiMaxScoreBytes; compact_lang_det_impl.h:159-161): the all-C
# single-doc path answers anything real at or under this (only
# adversarial >32K-script-flip constructions exceed its budget ladder,
# and those report failure so callers can fall back).
MAX_SCORE_BYTES = 160 << 10


def detect_one_native(text: str, tables: ScoringTables, reg: Registry):
    """One document through the all-C pipeline (pack -> C chunk scorer
    -> epilogue -> gate recursion; packer.cc detect_one_row): the fast
    path behind the public detect(). Returns the ldt_epilogue_flat
    14-lane row as a list, or None when the native library is
    unavailable or the text exceeds the C seam's 160KB scoring subset
    (the scalar engine scans everything, so oversized docs keep
    Python-visible behavior)."""
    lib = _load()
    if not lib:
        return None
    enc = text.encode("utf-8", errors="surrogatepass")
    if len(enc) > MAX_SCORE_BYTES:
        return None
    _ensure_init(tables, reg)
    out = (ctypes.c_int64 * 14)()
    if not lib.ldt_detect_one_full(enc, ctypes.c_int32(len(enc)), out):
        return None  # adversarial budget overflow: caller goes scalar
    return list(out)


def detect_batch_codes_native(texts: list[str], tables: ScoringTables,
                              reg: Registry,
                              n_threads: int = 0) -> np.ndarray | None:
    """Language ids for a small batch through the all-C pipeline
    (ldt_detect_batch_codes) — no device dispatch, so a tiny service
    flush answers in ~1ms instead of paying the backend's fixed
    dispatch latency. Returns None when the native library is
    unavailable or any document exceeds the 160KB C-path subset."""
    lib = _load()
    if not lib:
        return None
    B = len(texts)
    blob, bounds = _marshal_texts(texts)
    if int(np.diff(bounds).max(initial=0)) > MAX_SCORE_BYTES:
        return None
    _ensure_init(tables, reg)
    out = np.zeros(B, np.int32)
    if n_threads <= 0:
        import os
        n_threads = min(8, os.cpu_count() or 1)
    lib.ldt_detect_batch_codes(
        _ptr(blob, np.uint8), _ptr(bounds, np.int64),
        ctypes.c_int32(B), ctypes.c_int32(n_threads),
        _ptr(out, np.int32))
    return out


def epilogue_flat_native(rows: np.ndarray, cb: ChunkBatch, flags: int,
                         reg: Registry,
                         skip: np.ndarray | None = None) -> np.ndarray:
    """Chunk-major document epilogue (epilogue.cc ldt_epilogue_flat).

    rows: [G, 5] int32 chunk summaries in flat wire order.
    Returns the ldt_epilogue_batch [B, 14] contract."""
    lib = _load()
    if not lib:
        raise RuntimeError("native epilogue unavailable")
    B = cb.n_docs
    Dc = cb.direct_adds.shape[1]
    close, alt, figs = _epilogue_reg_arrays(reg)
    rows = np.ascontiguousarray(rows, dtype=np.int32)
    sk = np.ascontiguousarray(
        cb.fallback if skip is None else skip, dtype=np.uint8)
    out = np.zeros((B, 14), np.int64)
    lib.ldt_epilogue_flat(
        _ptr(rows, np.int32), _ptr(cb.doc_chunk_start, np.int64),
        _ptr(cb.n_chunks, np.int32), _ptr(cb.direct_adds, np.int32),
        _ptr(cb.text_bytes, np.int32), _ptr(sk, np.uint8),
        ctypes.c_int32(B), ctypes.c_int32(Dc), ctypes.c_int32(flags),
        _ptr(close, np.int32), _ptr(alt, np.int32), _ptr(figs, np.uint8),
        ctypes.c_int32(len(close)), _ptr(out, np.int64))
    return out


# -- batched document epilogue (epilogue.cc) --------------------------------

_epi_reg_cache: tuple = ()  # single slot: (registry object, arrays)


def _epilogue_reg_arrays(reg: Registry):
    """close_set / closest_alt / is_figs as flat arrays, cached for the
    last-used registry object (held by strong reference — never key by
    id(), CPython recycles addresses)."""
    global _epi_reg_cache
    if _epi_reg_cache and _epi_reg_cache[0] is reg:
        return _epi_reg_cache[1]
    n = reg.num_languages
    close = np.zeros(n, np.int32)
    for lang in range(n):
        close[lang] = reg.close_set(lang)
    alt = np.full(n, 26, np.int32)
    alt[:len(reg.closest_alt_lang)] = reg.closest_alt_lang.astype(np.int32)
    figs = np.zeros(n, np.uint8)
    for code in ("fr", "it", "de", "es"):
        figs[reg.code_to_lang[code]] = 1
    arrays = (close, alt, figs)
    _epi_reg_cache = (reg, arrays)
    return arrays
