"""Bit-parity of n-gram fingerprints vs the compiled reference oracle.

The scoring tables are keyed by these hashes; any divergence silently turns
hits into misses, so these tests fuzz broadly.
"""
import ctypes
import random

import numpy as np
import pytest

from language_detector_tpu.preprocess.hashing import (
    bi_hash_v2, octa_hash40, pair_hash, quad_hash_v2)

WORDS = [
    b"the", b"confiserie", b"chocolaterie", b"a", b"ab", b"abc", b"abcd",
    b"abcdefgh", b"abcdefghijkl", b"abcdefghijklmnopqrstuvwx",
    "ñandú".encode(), "vögel".encode(), "больж".encode(),
    "справочник".encode(), "الاتحاد".encode(), "ブログトップ".encode(),
    "中华人民共和国".encode(), "príliš".encode(), "žluťoučký".encode(),
]


def _buffers():
    rng = random.Random(42)
    cases = []
    for w in WORDS:
        for pre in (b" ", b"x"):
            for post in (b" ", b"y"):
                buf = b" " + pre + w + post + b"   \0\0\0\0\0\0\0\0"
                cases.append((buf, 2, len(w)))
    # random byte soup (printable + UTF-8-ish), random lengths
    for _ in range(200):
        n = rng.randint(1, 24)
        body = bytes(rng.randrange(0x21, 0xF5) for _ in range(n))
        buf = b"  " + body + b"    \0\0\0\0\0\0\0\0"
        cases.append((buf, 2, n))
    return cases


@pytest.mark.parametrize("fn,oname,maxlen", [
    (quad_hash_v2, "o_quadhash", 12),
    (octa_hash40, "o_octahash", 24),
    (bi_hash_v2, "o_bihash", 8),
])
def test_hash_parity(oracle, fn, oname, maxlen):
    ofn = getattr(oracle, oname)
    for buf, pos, n in _buffers():
        if n > maxlen and oname != "o_octahash":
            continue  # reference callers never exceed these lengths
        arr = np.frombuffer(buf, dtype=np.uint8)
        mine = fn(arr, np.array([pos]), np.array([n]))[0]
        theirs = ofn(buf, pos, n)
        assert int(mine) == int(theirs), (buf, pos, n)


def test_pair_hash_parity(oracle):
    rng = random.Random(7)
    for _ in range(100):
        a = rng.getrandbits(40)
        b = rng.getrandbits(40)
        assert int(pair_hash(a, b)) == oracle.o_pairhash(a, b)
