"""HTTP JSON batch detection service.

Behavior-compatible rebuild of the reference Go microservice (main.go,
handlers.go) over the batched TPU engine:

  GET  /   -> canned usage JSON                    (main.go:41-60, :150)
  POST /   -> {"request": [{"text": ...}, ...]} ->
              {"response": [{"iso6391code": ..., "name": ...}, ...]}
              (handlers.go:105-186); per-item "Missing text key" errors
              keep the batch going with overall HTTP 400; an unmapped
              language code answers name "Unknown" with HTTP 203
  else     -> 404 {"error": "Not found"}

Request validation mirrors GetRequests (handlers.go:33-69): Content-Type
must be application/json (400), the body is truncated at 1 MB before
parsing, and invalid JSON answers 400. @mention / http link words are
stripped before detection (StripExtras, handlers.go:198-210).

Metrics: Prometheus text format on a second port (main.go:137-147 series,
plus TPU-batch gauges: fallback-document count and batch flushes), and a
throughput log line every 1000 objects (main.go:209-218).

Ports come from LISTEN_PORT / PROMETHEUS_PORT env vars (main.go:91-116).
Run: python -m language_detector_tpu.service.server
"""
from __future__ import annotations

import json
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from .. import capture, faults, flightrec, knobs, slo, telemetry
from ..locks import make_lock
from . import wire
from .admission import (BREAKER_OPEN, BREAKER_STATE_NAMES,
                        AdmissionController, DeadlineExceeded,
                        degraded_detect)
from .batcher import Batcher
# contract helpers live in wire.py (shared with the asyncio front and
# the UDS lane); re-exported here for existing importers
from .wire import (BODY_LIMIT_BYTES, FragmentCache,  # noqa: F401
                   parse_post_body, post_detect, pre_detect,
                   strip_extras)

OBJECTS_PER_LOG = 1000                  # main.go:61

USAGE = {
    "result": {
        "id": "language-detector",
        "name": "language-detector",
        "description": "Determine language code from text",
        "in": {"text": {"type": "string"}},
        "out": {"iso6391code": {"type": "string"},
                "name": {"type": "string"}},
    }
}

_CODES_FILE = Path(__file__).parent / "cld_codes.json"


class Metrics:
    """Prometheus-style counters (main.go:137-147) + TPU batch stats.

    Request durations live in a real histogram
    (ldt_request_latency_ms, telemetry.REGISTRY — shared with the
    asyncio front); the reference's raw running-sum series
    `augmentation_request_duration_milliseconds` stays emitted for
    backward compatibility, derived from the histogram's sum."""

    def __init__(self):
        self._lock = make_lock("server.metrics")
        self.counters = {
            "augmentation_requests_total": 0,
            "augmentation_invalid_requests_total": 0,
            "augmentation_errors_logged_total": 0,
        }
        self.objects = {"successful": 0, "unsuccessful": 0}
        self.languages: dict = {}
        # live TPU-engine gauge source (set when a device engine exists):
        # () -> {"batches": int, "fallback_docs": int,
        #        "scalar_recursion_docs": int, "tier_*_dispatches": int,
        #        "retry_lane_dispatches": int, "dedup_docs": int}
        self.engine_stats = lambda: {}
        # live result-cache gauge source (set when the batcher cache is
        # enabled): () -> batcher.ResultCache.stats() dict or None
        self.cache_stats = lambda: None
        # live admission-control gauge source (set by DetectorService):
        # () -> admission.AdmissionController.stats() dict or None
        self.admission_stats = lambda: None
        # live readiness source (set by DetectorService): () ->
        # DetectorService.readiness() dict or None (the /readyz
        # contract, exported as ldt_ready and /debug/vars "ready")
        self.readiness = lambda: None
        # live device-pool gauge source (set when the engine runs a
        # DevicePool): () -> parallel.pool.DevicePool.stats() dict or
        # None (pool disabled — the gauges render 0)
        self.pool_stats = lambda: None
        # live dispatch-pipeline gauge source (set when a device engine
        # exists): () -> models/ngram.py pipeline_stats() dict or None
        # (overlap ratio, prefetch depth, staging-ring occupancy)
        self.pipeline_stats = lambda: None
        # shm ring lane sources (set by ShmRingServer.start when
        # LDT_SHM_DIR is set): () -> shmring snapshot / quarantine
        # stats dict or None (lane disabled — the gauges render 0)
        self.shm_stats = lambda: None
        self.quarantine_stats = lambda: None
        # fleet-shared result-cache source (set when the shared tier
        # attaches): () -> sharedcache.SharedResultCache.stats() dict
        # or None (tier disabled)
        self.shared_cache_stats = lambda: None
        # SLO engine + traffic-capture sources (module-level singletons
        # in slo.py / capture.py — armed by LDT_SLO / LDT_CAPTURE_DIR;
        # disabled -> None and the gauges render 0)
        self.slo_stats = slo.stats
        self.capture_stats = capture.stats

    def inc(self, name: str, amount: float = 1):
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + amount

    def observe_request_ms(self, ms: float):
        """One request's end-to-end latency into the shared histogram
        (replaces the old running-sum inc)."""
        telemetry.REGISTRY.histogram("ldt_request_latency_ms") \
            .observe(ms)

    def inc_object(self, status: str, amount: int = 1):
        with self._lock:
            self.objects[status] += amount

    def inc_language(self, name: str):
        with self._lock:
            self.languages[name] = self.languages.get(name, 0) + 1

    def add_languages(self, counts: dict):
        """Merge one request's per-language counts under a single lock
        (per-document inc calls cost ~3 lock round-trips per doc, which
        is real throughput on the single-core host)."""
        with self._lock:
            langs = self.languages
            for name, n in counts.items():
                langs[name] = langs.get(name, 0) + n

    _COUNTER_HELP = {
        "augmentation_requests_total":
            "Total HTTP requests served (main.go:137).",
        "augmentation_invalid_requests_total":
            "Requests rejected for shape/route/content-type.",
        "augmentation_errors_logged_total":
            "Error responses logged.",
    }

    def render(self) -> str:
        """Full Prometheus exposition body: every family carries # HELP
        and # TYPE, label values are escaped, and the whole output
        passes a strict parser (tests/test_telemetry.py lint)."""
        fams: list = []
        with self._lock:
            for k, v in sorted(self.counters.items()):
                fams.append((k, "counter",
                             self._COUNTER_HELP.get(k, k),
                             [(k, None, v)]))
            fams.append((
                "augmentation_objects_processed_total", "counter",
                "Documents processed, by outcome (main.go:141).",
                [("augmentation_objects_processed_total",
                  {"status": s}, v)
                 for s, v in sorted(self.objects.items())]))
            fams.append((
                "augmentation_detected_language", "counter",
                "Documents per detected language name (main.go:144).",
                [("augmentation_detected_language",
                  {"language": name}, v)
                 for name, v in sorted(self.languages.items())]))
        # legacy running-sum series (the reference's raw duration
        # counter, main.go:139) — now derived from the histogram's sum
        # so old dashboards keep working next to the real histogram
        _, req_sum, _, _ = telemetry.REGISTRY.histogram(
            "ldt_request_latency_ms").snapshot()
        fams.append((
            "augmentation_request_duration_milliseconds", "counter",
            "DEPRECATED running sum of request milliseconds; prefer "
            "ldt_request_latency_ms (histogram).",
            [("augmentation_request_duration_milliseconds", None,
              round(req_sum, 6))]))
        # ldt_* gauge/counter families below render through
        # telemetry.metric_family, which looks TYPE and HELP up in the
        # central telemetry.METRICS declaration — the metric-registry
        # analyzer (tools/lint) keeps that declaration, this code, and
        # docs/OBSERVABILITY.md in sync
        fam = telemetry.metric_family

        def one(name, value):
            return fam(name, [(name, None, value)])

        # engine gauges, read live (the engine locks its own stats);
        # ldt_device_dispatches_total is what the recycle watcher meters
        # against LDT_MAX_DISPATCHES (excludes all-C tiny flushes, which
        # burn no recycle budget)
        es = self.engine_stats()
        fams.append(one("ldt_batch_flushes_total",
                        es.get("batches", 0)))
        fams.append(one("ldt_device_dispatches_total",
                        es.get("device_dispatches", 0)))
        fams.append(one("ldt_fallback_documents_total",
                        es.get("fallback_docs", 0) +
                        es.get("scalar_recursion_docs", 0)))
        # bucketed-scheduler lanes (models/ngram.py _detect_stream)
        fams.append(fam("ldt_tier_dispatches_total",
                        [("ldt_tier_dispatches_total", {"tier": tier},
                          es.get(f"tier_{tier}_dispatches", 0))
                         for tier in ("short", "mid", "long", "mixed")]))
        fams.append(one("ldt_retry_lane_dispatches_total",
                        es.get("retry_lane_dispatches", 0)))
        fams.append(one("ldt_dedup_documents_total",
                        es.get("dedup_docs", 0)))
        # result cache (service/batcher.py, LDT_RESULT_CACHE_MB)
        cs = self.cache_stats()
        fams.append(one("ldt_result_cache_hit_rate",
                        cs["hit_rate"] if cs else 0.0))
        fams.append(one("ldt_result_cache_hits_total",
                        cs["hits"] if cs else 0))
        fams.append(one("ldt_result_cache_bytes",
                        cs["bytes"] if cs else 0))
        # admission control / graceful degradation (service/admission.py;
        # ldt_shed_total and ldt_deadline_expired_total are registry
        # counters and render with the families below)
        ad = self.admission_stats() or {}
        fams.append(one("ldt_admission_queue_docs",
                        ad.get("queue_docs", 0)))
        fams.append(one("ldt_admission_queue_bytes",
                        ad.get("queue_bytes", 0)))
        fams.append(one("ldt_admission_inflight",
                        ad.get("inflight", 0)))
        fams.append(one("ldt_brownout_level",
                        ad.get("brownout_level", 0)))
        fams.append(one("ldt_breaker_state",
                        ad.get("breaker_state", 0)))
        # per-tenant queue occupancy (X-LDT-Tenant quotas); tenants
        # with no live work carry no sample — the family still renders
        fams.append(fam("ldt_tenant_queue_bytes",
                        [("ldt_tenant_queue_bytes", {"tenant": t},
                          v.get("queue_bytes", 0))
                         for t, v in sorted(
                             (ad.get("tenants") or {}).items())]))
        # device-pool lane rotation (parallel/pool.py; the eviction /
        # re-admission / failover / hedge counters are registry
        # counters and render with the families below)
        ps = self.pool_stats() or {}
        fams.append(one("ldt_pool_lanes_total",
                        ps.get("lanes_total", 0)))
        fams.append(one("ldt_pool_lanes_active",
                        ps.get("lanes_active", 0)))
        # dispatch pipeline (models/ngram.py pipeline_stats; the
        # donation-hit and longdoc-chunk counters are registry counters
        # and render with the families below)
        pl = self.pipeline_stats() or {}
        fams.append(one("ldt_pipeline_overlap_ratio",
                        pl.get("overlap_ratio", 0.0)))
        fams.append(one("ldt_pipeline_depth", pl.get("depth", 0)))
        fams.append(one("ldt_pipeline_staging_ring_occupancy",
                        pl.get("staging_ring_occupancy", 0)))
        # shm ring ingest lane (service/shmring.py; the frame /
        # reclaim / quarantine counters are registry counters and
        # render with the families below)
        sh = self.shm_stats() or {}
        fams.append(one("ldt_shm_rings", sh.get("rings", 0)))
        fams.append(one("ldt_shm_slots_free", sh.get("slots_free", 0)))
        # readiness + supervision (docs/ROBUSTNESS.md): ldt_ready
        # mirrors /readyz, the generation gauge is set by the
        # supervisor through the child's environment
        rd = self.readiness()
        fams.append(one("ldt_ready",
                        1 if rd is not None and rd.get("ok") else 0))
        fams.append(one("ldt_warmup_ms",
                        rd.get("warmup_ms", 0) if rd else 0))
        fams.append(one("ldt_worker_generation",
                        knobs.get_int("LDT_WORKER_GENERATION") or 0))
        # SLO engine (slo.py; ldt_slo_events_total and
        # ldt_slo_breaches_total are registry counters and render with
        # the families below)
        sl = self.slo_stats() or {}
        fams.append(one("ldt_slo_alert",
                        1 if sl.get("alert") else 0))
        fams.append(fam("ldt_slo_burn_rate",
                        [("ldt_slo_burn_rate", {"window": "fast"},
                          sl.get("burn_fast", 0.0)),
                         ("ldt_slo_burn_rate", {"window": "slow"},
                          sl.get("burn_slow", 0.0))]))
        fams.append(one("ldt_slo_budget_remaining",
                        sl.get("budget_remaining", 1.0)))
        # traffic-capture plane (capture.py) — the *_total series are
        # registry counters; ring occupancy is the live gauge here
        cp = self.capture_stats() or {}
        fams.append(one("ldt_capture_ring_occupancy",
                        cp.get("ring_occupancy", 0)))
        # runtime config plane (configplane.py;
        # ldt_config_applies_total is a registry counter and renders
        # with the families below)
        from .. import configplane
        cfg = configplane.stats() or {}
        fams.append(one("ldt_config_generation",
                        cfg.get("generation", 0)))
        fams.append(one("ldt_config_state",
                        {"idle": 0, "staged": 1, "probation": 2,
                         "committed": 3, "rolled_back": 4}.get(
                             cfg.get("state", "idle"), 0)))
        # shared telemetry registry: stage/request histograms + compile
        # counters (both fronts render the same registry)
        fams.extend(telemetry.REGISTRY.families())
        return telemetry.render_exposition(fams)


class DetectorService:
    """Engine + batcher + metrics shared by all handler threads."""

    def __init__(self, max_batch: int = 16384, max_delay_ms: float = 5.0,
                 use_device: bool = True, start_batcher: bool = True,
                 cache_bytes: int | None = None,
                 admission: AdmissionController | None = None):
        """start_batcher=False skips the sync Batcher (its collector
        thread + flush pool) for fronts that bring their own batching
        layer (aioserver.AioBatcher). cache_bytes: batcher result-cache
        budget; None reads LDT_RESULT_CACHE_MB (0/unset = disabled).
        admission: overload controller; None builds one from the LDT_*
        env knobs (all off by default — tests inject configured ones)."""
        self.metrics = Metrics()
        self.admission = admission or AdmissionController.from_env()
        self.metrics.admission_stats = self.admission.stats
        self.known = json.loads(_CODES_FILE.read_text())
        # per-code pre-serialized response fragments (the reference
        # pre-renders its static JSON for the same reason, main.go:150-166;
        # here the per-item object is a pure function of the code, so the
        # whole response body assembles from cached byte fragments
        # instead of building dicts + json.dumps per document); the
        # cache type lives in wire.py, shared with the asyncio front
        self._frag_cache = FragmentCache(self.known)
        # throughput-window counters: handler threads race on the
        # read-modify-write in log_processed, so they get their own lock
        self._log_lock = make_lock("server.processed")
        self._num_processed = 0
        self._window_start = time.time()
        # flipped true by _make_detect once the table artifact is
        # actually loaded; /readyz reports false until then (and an
        # ArtifactError propagates out of __init__ — startup fails loud)
        self._artifact_loaded = False
        # which artifact is serving (LDT_ARTIFACT_PATH or the packaged
        # default); service/swap.py rebinds it on a hot swap
        self._artifact_path = knobs.get_str("LDT_ARTIFACT_PATH")
        # serializes in-process hot swaps (service/swap.swap_artifact);
        # detect closures never take it — they read the engine/tables
        # reference once per call, and a swap is one atomic rebind
        self._swap_lock = make_lock("server.swap")
        self._swap_count = 0
        # startup warmup (LDT_WARMUP): /readyz holds false until warm()
        # pre-compiles the bucket ladder; off -> born warm
        self._warmed = not knobs.get_bool("LDT_WARMUP")
        self._warmup_ms = 0.0
        # in-flight HTTP requests on the threaded front (main()'s
        # graceful drain waits on it; shares the _log_lock)
        self._inflight_http = 0
        self._detect = self._make_detect(use_device)
        self.metrics.readiness = self.readiness
        # pre-touch both swap outcomes so a scrape shows the series at
        # 0 before any drill (mirrors the admission shed pre-touch)
        for result in ("ok", "error"):
            telemetry.REGISTRY.counter_inc("ldt_swap_total", 0,
                                           result=result)
        if cache_bytes is None:
            mb = knobs.get_float("LDT_RESULT_CACHE_MB")
            cache_bytes = int((mb or 0) * 1e6)
        # resolved budget, for fronts that bring their own batching
        # layer (aioserver wires the same cache into its AioBatcher)
        self.cache_bytes = cache_bytes
        self.batcher = Batcher(self._detect, max_batch=max_batch,
                               max_delay_ms=max_delay_ms,
                               cache_bytes=cache_bytes) \
            if start_batcher else None
        if self.batcher is not None and self.batcher._cache is not None:
            self.metrics.cache_stats = self.batcher.cache_stats
            cache = self.batcher._cache
            # namespace the caches to the serving artifact's content
            # digest FROM BOOT, not just after the first swap: during a
            # fleet roll, members booted on the new artifact and
            # members swapped onto it must land in the same shared-
            # cache epoch — and members still on the old artifact in a
            # different one (zero cross-artifact hits by construction)
            if self._artifact_path:
                from .. import artifact as artifact_mod
                boot_epoch = artifact_mod.artifact_digest(
                    self._artifact_path)
                if boot_epoch:
                    cache.set_epoch(boot_epoch)
            if cache._shared is not None:
                self.metrics.shared_cache_stats = cache._shared.stats

    def _load_tables(self):
        """Initial table load honoring LDT_ARTIFACT_PATH. An explicit
        path loads its own mmap (bypassing tables.py's per-path cache —
        the same loader the hot swap uses); unset keeps the packaged
        default."""
        from ..tables import ScoringTables, load_tables
        if self._artifact_path:
            return ScoringTables.load_mmap(Path(self._artifact_path))
        return load_tables()

    def _make_detect(self, use_device: bool):
        from ..registry import registry
        self._registry = registry
        self._tables = None
        if use_device:
            try:
                # an ArtifactError (bad magic / truncated / version
                # mismatch) is NOT swallowed into the scalar fallback:
                # it propagates out of __init__ so startup fails with
                # the actionable message instead of silently serving
                # degraded
                from ..models.ngram import NgramBatchEngine
                eng = NgramBatchEngine(
                    tables=self._load_tables()
                    if self._artifact_path else None)
                self._artifact_loaded = True
                self._engine = eng
                metrics = self.metrics
                breaker = self.admission.breaker

                # engine TPU gauges (ldt_*) are read live at render
                # time — per-flush before/after deltas would race now
                # that flushes run concurrently on worker pools. The
                # snapshot copies UNDER the engine's stats lock: a bare
                # dict(eng.stats) could race a concurrent key insertion
                # (dict resize mid-copy raises RuntimeError). Reading
                # through self._engine (not a captured engine) keeps
                # the gauges live across hot swaps
                metrics.engine_stats = \
                    lambda: self._engine.stats_snapshot()
                # device-pool wiring (read through self._engine so a
                # hot swap's rebuilt engine is picked up): lane gauges
                # for /metrics, and lost lane capacity feeding the
                # brownout ladder's load signal

                def pool_of():
                    return getattr(self._engine, "pool", None)

                def pool_stats():
                    p = pool_of()
                    return p.stats() if p is not None else None

                metrics.pool_stats = pool_stats
                self.admission.attach_pool(pool_of)
                # dispatch-pipeline gauges (same hot-swap-safe read
                # through self._engine as the pool wiring above)
                metrics.pipeline_stats = \
                    lambda: self._engine.pipeline_stats()

                def detect(texts, trace=None):
                    # codes-only engine path: the handler needs just the
                    # ISO code per item (wrapper.cc:7-16 semantics), and
                    # skipping result materialization matters at 16K-doc
                    # flushes on a single-core host. batch_size 8192
                    # splits a full-size flush into 2+ slices so pack,
                    # device transfer, and fetch pipeline INSIDE the
                    # flush (a single 16K slice runs serially: measured
                    # 63K -> 75K docs/sec through the asyncio front).
                    # The circuit breaker wraps exactly this seam: a
                    # tripped device routes flushes to the scalar
                    # engine (identical answers, no device dispatch)
                    # until a half-open probe succeeds. The engine
                    # reference is read once per call: a hot swap
                    # (service/swap.py) rebinds self._engine between
                    # flushes and in-flight calls finish on the engine
                    # they started with
                    engine = self._engine
                    if not breaker.allow_device():
                        return self.scalar_codes(texts, trace=trace)
                    t0 = time.monotonic()
                    try:
                        out = engine.detect_codes(texts,
                                                  batch_size=8192,
                                                  trace=trace)
                    except Exception:
                        breaker.record_failure()
                        raise
                    breaker.record_success(
                        (time.monotonic() - t0) * 1e3)
                    return out
                return detect
            except (ImportError, RuntimeError):
                pass
        from ..engine_scalar import detect_scalar
        tables = self._load_tables()
        self._artifact_loaded = True
        self._engine = None
        self._tables = tables

        def detect(texts, trace=None):
            # same per-call reference read as the device closure: a
            # hot swap rebinds self._tables atomically
            tables = self._tables
            t0 = time.monotonic()
            out = [registry.code(
                detect_scalar(t, tables, registry).summary_lang)
                for t in texts]
            telemetry.observe_stage("scalar_detect", t0, trace=trace)
            return out
        return detect

    def scalar_codes(self, texts: list, trace=None) -> list:
        """Scalar-engine codes for the degradation paths (breaker open,
        brownout level 2): exact answers, no batcher, no device."""
        from ..engine_scalar import detect_scalar
        tables = self._engine.tables if self._engine is not None \
            else self._tables
        reg = self._registry
        t0 = time.monotonic()
        out = [reg.code(detect_scalar(t, tables, reg).summary_lang)
               for t in texts]
        telemetry.observe_stage("scalar_detect", t0, trace=trace)
        return out

    def warm(self) -> float:
        """Pre-compile the bucket ladder's jitted shapes so the first
        real request doesn't pay XLA compilation (LDT_WARMUP gates
        /readyz on this). The batch deliberately exceeds the tiny-batch
        all-C threshold (TINY_BATCH_C_PATH=64 docs) with mixed lengths
        so the short/mid tier lanes actually launch; returns (and
        records) the wall duration in ms."""
        t0 = time.monotonic()
        base = ("the quick brown fox jumps over the lazy dog ",
                "el veloz murcielago hindu comia feliz cardillo ",
                "portez ce vieux whisky au juge blond qui fume ")
        texts = [base[i % 3] * (1 + (i % 4) * 8) + str(i)
                 for i in range(96)]
        try:
            self._detect(texts)
        finally:
            self._warmup_ms = (time.monotonic() - t0) * 1e3
            self._warmed = True
        return self._warmup_ms

    def http_inflight(self) -> int:
        """Threaded-front in-flight request count (main()'s graceful
        drain polls it after serve_forever returns)."""
        with self._log_lock:
            return self._inflight_http

    def _http_enter(self):
        with self._log_lock:
            self._inflight_http += 1

    def _http_exit(self):
        with self._log_lock:
            self._inflight_http -= 1

    def readiness(self) -> dict:
        """The /readyz contract (docs/ROBUSTNESS.md): ready means the
        artifact is loaded, startup warmup finished (when LDT_WARMUP
        is on), the device breaker is not open, and the brownout ladder
        sits below the shed level. Liveness (/healthz) is unconditional
        — a not-ready process is alive, just asking the balancer to
        route around it."""
        bstate = self.admission.breaker.stats()["state"]
        level, _ = self.admission.ladder.snapshot()
        ok = (self._artifact_loaded and self._warmed and
              bstate != BREAKER_OPEN and level < 3)
        return {"ok": ok,
                "artifact_loaded": self._artifact_loaded,
                "warmed": self._warmed,
                "warmup_ms": round(self._warmup_ms, 3),
                "breaker": BREAKER_STATE_NAMES[bstate],
                "brownout_level": level}

    def detect_codes(self, texts: list, trace=None) -> list:
        fut = self.batcher.submit(texts, trace=trace)
        return fut.result(
            timeout=knobs.get_float("LDT_FLUSH_TIMEOUT_SEC") or 60.0)

    def detect_codes_degraded(self, texts: list, trace=None) -> list:
        """Brownout level-2 serving: result cache (when enabled) +
        scalar engine, bypassing the batcher/device entirely."""
        cache = self.batcher._cache if self.batcher is not None \
            else None
        return degraded_detect(texts, self.scalar_codes, cache=cache,
                               trace=trace)

    def detect_spans_codes(self, texts: list, trace=None) -> list:
        """Per-span serving (LDT_SPANS=1 requests): list of
        (code, span_records) per doc, span_records =
        [(byte_offset, byte_len, code, percent, reliable), ...] tiling
        the document (docs/ACCURACY.md span contract). Bypasses the
        codes batcher — the span lane is low-volume and its pack shape
        (per-sub-doc split) doesn't share the codes path's dedup/cache
        keys; device engine when available, scalar oracle otherwise
        (bit-identical either way, tests/test_spans.py)."""
        reg = self._registry
        t0 = time.monotonic()
        if self._engine is not None:
            rs = self._engine.detect_spans(texts)
        else:
            from ..engine_scalar import detect_scalar_spans
            tables = self._tables
            rs = [detect_scalar_spans(t, tables, reg) for t in texts]
        telemetry.observe_stage("spans_detect", t0, trace=trace)
        return [(reg.code(r.summary_lang), r.spans or []) for r in rs]

    def log_processed(self, amount: int = 1):
        """Throughput log every OBJECTS_PER_LOG objects (main.go:209).
        Called from every handler thread, so the window counters live
        under their own lock — the unlocked += was a lost-update race
        (and could double-print a window)."""
        with self._log_lock:
            self._num_processed += amount
            if self._num_processed < OBJECTS_PER_LOG:
                return
            n = self._num_processed
            took = time.time() - self._window_start
            self._num_processed = 0
            self._window_start = time.time()
        rate = n / max(took, 1e-9)
        print(json.dumps({
            "msg": f"Processed {n} objects in "
                   f"{took:.3f}s ({rate:.2f} per second)",
            "took": f"{took:.3f}s",
            "throughput": f"{rate:.2f}"}), flush=True)


def health_response(svc: DetectorService, path: str):
    """(status, body bytes) for /healthz and /readyz — one contract
    shared by both fronts and both ports (docs/ROBUSTNESS.md).
    /healthz is pure liveness: the process answers, so it is alive.
    /readyz answers 200 only when readiness() says ok, 503 otherwise,
    and the body carries the component breakdown either way so an
    operator's curl explains itself."""
    if path == "/healthz":
        return 200, b'{"status":"ok"}'
    r = svc.readiness()
    return (200 if r["ok"] else 503), json.dumps(r).encode()


class Handler(BaseHTTPRequestHandler):
    service: DetectorService  # injected by make_server
    protocol_version = "HTTP/1.1"
    # TCP_NODELAY + buffered single-send responses: without these, the
    # unbuffered multi-segment response interacts with Nagle + delayed
    # ACK for a ~40ms stall on EVERY keep-alive request (measured 44ms
    # -> 0.2ms per request on loopback)
    disable_nagle_algorithm = True
    wbufsize = 65536

    # -- helpers ------------------------------------------------------------

    def _send_json(self, status: int, payload: bytes, headers=None):
        self.send_response(status)
        self.send_header("Content-Type", "application/json; charset=utf-8")
        self.send_header("Content-Length", str(len(payload)))
        if headers:
            for k, v in headers.items():
                self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def _send_buffers(self, status: int, buffers: list, headers=None):
        """writev-style twin of _send_json: Content-Length is the sum
        of the fragments and the body goes out via writelines, so the
        batch envelope is never concatenated host-side."""
        self.send_response(status)
        self.send_header("Content-Type", "application/json; charset=utf-8")
        self.send_header("Content-Length",
                         str(sum(len(b) for b in buffers)))
        if headers:
            for k, v in headers.items():
                self.send_header(k, v)
        self.end_headers()
        self.wfile.writelines(buffers)

    def _send_error_json(self, message: str, status: int, headers=None):
        self.service.metrics.inc("augmentation_errors_logged_total")
        self._send_json(status,
                        json.dumps({"error": message}).encode(),
                        headers=headers)

    def log_message(self, fmt, *args):  # quiet access log
        pass

    def handle(self):
        # accept fault seam: an injected error here models a connection
        # dropped before any byte is read — the client sees a reset,
        # never a half-written response
        if faults.ACTIVE is not None:
            try:
                faults.hit("accept")
            except faults.FaultInjected:
                self.close_connection = True
                return
        super().handle()

    # -- routes -------------------------------------------------------------

    def do_GET(self):
        t0 = time.time()
        if self.path in ("/", ""):
            self._send_json(200, json.dumps(USAGE).encode())
        elif self.path in ("/healthz", "/readyz"):
            status, body = health_response(self.service, self.path)
            self._send_json(status, body)
        else:
            self.service.metrics.inc("augmentation_invalid_requests_total")
            self._send_json(404, b'{"error":"Not found"}')
        self._finish_metrics(t0)

    def do_POST(self):
        # in-flight accounting: main()'s graceful drain (recycle /
        # SIGTERM cutover) waits for this count to hit zero after the
        # accept loop stops, so a full-size flush mid-request survives
        self.service._http_enter()
        try:
            t0 = time.time()
            body = self._consume_body()
            if body is None:  # oversize: 413 sent, connection closing
                self._finish_metrics(t0)
                return
            if self.path not in ("/", ""):
                self.service.metrics.inc(
                    "augmentation_invalid_requests_total")
                self._send_json(404, b'{"error":"Not found"}')
                self._finish_metrics(t0)
                return
            self._detector(body)
            # the detector path observed its own (traced) duration via
            # telemetry.finish_request — only count the request here
            self._finish_metrics(t0, traced=True)
        finally:
            self.service._http_exit()

    def _finish_metrics(self, t0: float, traced: bool = False):
        m = self.service.metrics
        m.inc("augmentation_requests_total")
        if not traced:
            m.observe_request_ms((time.time() - t0) * 1e3)

    # oversize drain ceiling: keep reading a rejected body up to this
    # much so a mid-upload client sees the 413 instead of EPIPE, but
    # never let a hostile Content-Length make us stream gigabytes
    DRAIN_CAP_BYTES = 8 * BODY_LIMIT_BYTES

    def _consume_body(self) -> "bytes | None":
        """Read the request body. A body DECLARING more than the 1 MB
        contract limit is rejected with 413 and the connection closed —
        the old truncate-then-parse answered a misleading 400. The
        rejected body is drained (discarded, up to DRAIN_CAP_BYTES) so
        a client still mid-upload receives the response rather than a
        broken pipe; past the cap we just close. Returns None when the
        request was answered here (413 path)."""
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
        except ValueError:
            length = 0  # malformed header: empty body -> 400 invalid JSON
        if length > BODY_LIMIT_BYTES:
            m = self.service.metrics
            m.inc("augmentation_invalid_requests_total")
            m.inc_object("unsuccessful")
            self.close_connection = True
            remaining = min(length, self.DRAIN_CAP_BYTES)
            while remaining > 0:
                chunk = self.rfile.read(min(remaining, 65536))
                if not chunk:
                    break
                remaining -= len(chunk)
            hdrs = {"Connection": "close"}
            rid = wire.clean_request_id(
                self.headers.get(wire.REQUEST_ID_HEADER))
            if rid:  # the id echoes even on a rejected request
                hdrs[wire.REQUEST_ID_HEADER] = rid
            self._send_error_json("Request body exceeds 1MB limit", 413,
                                  headers=hdrs)
            return None
        return self.rfile.read(max(length, 0))

    def _detector(self, body: bytes):
        """LanguageDetectorHandler (handlers.go:105-186)."""
        svc = self.service
        telemetry.REGISTRY.counter_inc("ldt_http_requests_total",
                                       lane="tcp")
        trace = telemetry.Trace()
        rid = wire.clean_request_id(
            self.headers.get(wire.REQUEST_ID_HEADER)) \
            or wire.gen_request_id()
        trace.request_id = rid
        echo = {wire.REQUEST_ID_HEADER: rid}
        flightrec.emit_event("request_start", request_id=rid,
                             lane="tcp")
        # completion-meta base shared by every finish_request exit on
        # this handler: the capture plane records request shape
        # (bytes -> size bucket, priority flag) alongside the outcome
        base = {"front": "sync", "bytes": len(body),
                "priority":
                    self.headers.get("X-LDT-Priority") is not None}
        t = trace.t0
        pre, err = wire.parse_request(
            svc, self.headers.get("Content-Type"), body)
        if err is not None:
            self._send_json(*err, headers=echo)
            telemetry.finish_request(
                trace, meta=dict(base, status=err[0]))
            return
        t = telemetry.observe_stage("parse", t, trace=trace)
        texts, slots, responses, status = pre
        adm = svc.admission
        admit = None
        if texts:
            admit = adm.try_admit(
                texts,
                priority=self.headers.get("X-LDT-Priority") is not None,
                tenant=self.headers.get("X-LDT-Tenant"))
            # tenant before the shed branch: sheds must carry the
            # throttled tenant's identity into SLO/capture
            trace.tenant = admit.tenant
            if admit.shed:
                svc.metrics.inc("augmentation_errors_logged_total")
                self._send_json(
                    admit.status,
                    json.dumps({"error": admit.message}).encode(),
                    headers=dict(
                        echo, **{"Retry-After":
                                 str(admit.retry_after)}))
                telemetry.finish_request(
                    trace, meta=dict(base, docs=len(texts),
                                     status=admit.status,
                                     shed=admit.reason))
                return
            trace.deadline = adm.deadline_from_header(
                self.headers.get("X-LDT-Deadline-Ms"))
            if admit.level >= 1 and not admit.probe:
                # pool probe vehicles keep retry rights: a lost probe
                # batch must fail over, not 500 (admission.Admit.probe)
                trace.no_retry = True
        # per-span verdicts (LDT_SPANS=1 server side + X-LDT-Spans on
        # the request); degrade paths drop to plain codes, so brownout
        # behavior is identical with spans on or off
        want_spans = (self.headers.get("X-LDT-Spans") is not None
                      and knobs.get_bool("LDT_SPANS"))
        spans_list = None
        try:
            if admit is not None and admit.degrade:
                codes = svc.detect_codes_degraded(texts, trace=trace)
            elif want_spans:
                pairs = svc.detect_spans_codes(texts, trace=trace) \
                    if texts else []
                codes = [c for c, _ in pairs]
                spans_list = [s for _, s in pairs]
            else:
                codes = svc.detect_codes(texts, trace=trace) \
                    if texts else []
        except DeadlineExceeded:
            svc.metrics.inc("augmentation_errors_logged_total")
            self._send_json(
                504, b'{"error":"deadline expired before dispatch"}',
                headers=echo)
            telemetry.finish_request(
                trace, meta=dict(base, docs=len(texts), status=504))
            return
        except (TimeoutError, FuturesTimeout):
            # flush future timed out (LDT_FLUSH_TIMEOUT_SEC): the
            # device/batcher is wedged, not the request malformed — 504
            # with the trace annotated, mirroring the aio front (on
            # 3.10 concurrent.futures.TimeoutError is its own type;
            # 3.11+ aliases it to the builtin)
            svc.metrics.inc("augmentation_errors_logged_total")
            self._send_json(504, b'{"error":"detection timed out"}',
                            headers=echo)
            telemetry.finish_request(
                trace, meta=dict(base, docs=len(texts), status=504,
                                 timeout="flush"))
            return
        except Exception as e:  # noqa: BLE001 - every doc resolves
            # the chaos invariant: an injected (or real) batcher/engine
            # error answers a typed 500, never a reset connection
            print(json.dumps({"msg": "detect failed",
                              "error": repr(e)}), flush=True)
            svc.metrics.inc("augmentation_errors_logged_total")
            self._send_json(500, b'{"error":"internal error"}',
                            headers=echo)
            telemetry.finish_request(
                trace, meta=dict(base, docs=len(texts), status=500))
            return
        finally:
            if admit is not None:
                adm.release(admit)
        t = telemetry.observe_stage("detect", t, trace=trace)
        status, buffers = wire.post_detect(
            svc, codes, slots, responses, status, spans=spans_list)
        telemetry.observe_stage("encode", t, trace=trace)
        self._send_buffers(status, buffers, headers=echo)
        telemetry.finish_request(
            trace, meta=dict(base, docs=len(texts), status=status))


# shared contract logic (parse_post_body / pre_detect / post_detect /
# strip_extras) moved to wire.py — re-exported at the top of this module


class MetricsHandler(BaseHTTPRequestHandler):
    service: DetectorService
    disable_nagle_algorithm = True
    wbufsize = 65536

    def log_message(self, fmt, *args):
        pass

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        status = 200
        if path in ("/healthz", "/readyz"):
            status, body = health_response(self.service, path)
            ctype = "application/json; charset=utf-8"
        elif path == "/debug/vars":
            body = json.dumps(
                telemetry.debug_vars(self.service.metrics),
                indent=2).encode()
            ctype = "application/json; charset=utf-8"
        elif path == "/sloz":
            body = json.dumps(slo.sloz(), indent=2).encode()
            ctype = "application/json; charset=utf-8"
        elif path == "/configz":
            from .. import configplane
            body = json.dumps(configplane.handle_get(),
                              indent=2).encode()
            ctype = "application/json; charset=utf-8"
        elif path == "/debug/slow":
            ring = telemetry.REGISTRY.slow
            body = json.dumps(
                {"threshold_ms": ring.threshold_ms,
                 "capacity": ring.capacity,
                 "recorded": ring.recorded,
                 "traces": ring.snapshot()}, indent=2).encode()
            ctype = "application/json; charset=utf-8"
        else:
            body = self.service.metrics.render().encode()
            ctype = "text/plain; version=0.0.4"
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        """POST /swap: in-process artifact hot swap (service/swap.py).
        POST /profilez: arm one bounded jax.profiler window
        (profiling.py). POST /configz: runtime mutable-knob apply with
        SLO-watched probation (configplane.py). All live on the
        metrics port — operator actions, not client traffic."""
        path = self.path.split("?", 1)[0]
        if path == "/profilez":
            from .. import profiling
            status, payload = profiling.arm()
            self._answer(status, json.dumps(payload).encode())
            return
        if path == "/configz":
            from .. import configplane
            try:
                length = int(self.headers.get("Content-Length", 0)
                             or 0)
            except ValueError:
                length = 0
            body = self.rfile.read(max(min(length, 65536), 0))
            status, payload = configplane.handle_post(body)
            self._answer(status, json.dumps(payload).encode())
            return
        if path != "/swap":
            self._answer(404, b'{"error":"Not found"}')
            return
        from . import swap as swap_mod
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
        except ValueError:
            length = 0
        body = self.rfile.read(max(min(length, 65536), 0))
        try:
            doc = json.loads(body.decode("utf-8") or "{}")
        except (ValueError, UnicodeDecodeError):
            self._answer(400, b'{"error":"invalid JSON body"}')
            return
        apath = (doc.get("path") if isinstance(doc, dict) else None) \
            or knobs.get_str("LDT_ARTIFACT_PATH")
        if not apath:
            self._answer(400, b'{"error":"no artifact path: POST '
                              b'{\\"path\\":...} or set '
                              b'LDT_ARTIFACT_PATH"}')
            return
        try:
            info = swap_mod.swap_artifact(self.service, apath)
        except swap_mod.SwapError as e:
            self._answer(409, json.dumps({"error": str(e)}).encode())
            return
        self._answer(200, json.dumps(info).encode())

    def _answer(self, status: int, body: bytes):
        self.send_response(status)
        self.send_header("Content-Type",
                         "application/json; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _make_http_server(addr: tuple, handler) -> ThreadingHTTPServer:
    """ThreadingHTTPServer, optionally bound with SO_REUSEPORT
    (LDT_REUSEPORT) so an old and a standby worker generation can
    overlap on the same port during a blue/green swap."""
    if not knobs.get_bool("LDT_REUSEPORT"):
        return ThreadingHTTPServer(addr, handler)
    import socket
    httpd = ThreadingHTTPServer(addr, handler,
                                bind_and_activate=False)
    try:
        httpd.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT,
                                1)
        httpd.server_bind()
        httpd.server_activate()
    except OSError:
        httpd.server_close()
        raise
    return httpd


def make_server(port: int = 0, metrics_port: int = 0,
                service: DetectorService | None = None):
    """Build (but don't run) the HTTP + metrics servers; port 0 picks
    ephemeral ports (tests)."""
    svc = service or DetectorService()
    handler = type("BoundHandler", (Handler,), {"service": svc})
    httpd = _make_http_server(("", port), handler)
    mhandler = type("BoundMetricsHandler", (MetricsHandler,),
                    {"service": svc})
    metricsd = _make_http_server(("", metrics_port), mhandler)
    return httpd, metricsd, svc


def _recycle_watch_thread(svc: DetectorService, httpd):
    """Threaded-front twin of aioserver._recycle_watch: planned worker
    self-recycle past LDT_MAX_DISPATCHES / LDT_MAX_RSS_MB (the tunneled
    backend's per-dispatch RSS leak, docs/PERF.md). No thread when
    neither bound is set."""
    from .recycle import (check_interval_sec, limits_from_env,
                          should_recycle)
    max_d, max_r = limits_from_env()
    if max_d is None and max_r is None:
        return

    def run():
        while True:
            time.sleep(check_interval_sec())
            stats = svc.metrics.engine_stats()
            # the leak tracks DEVICE dispatches; all-C tiny flushes
            # don't touch the plugin and must not burn recycle budget
            n = stats.get("device_dispatches", stats.get("batches", 0))
            reason = should_recycle(n, max_d, max_r)
            if reason:
                print(json.dumps(
                    {"msg": f"recycling worker: {reason}"}), flush=True)
                # flag + shutdown; the MAIN thread exits with the
                # recycle code after serve_forever returns (a daemon
                # thread racing os._exit against the interpreter's own
                # exit would sometimes lose and report rc=0)
                httpd._ldt_recycle = True
                httpd.shutdown()  # finish in-flight, stop accepting
                return

    threading.Thread(target=run, daemon=True,
                     name="ldt-recycle").start()


def main():
    import signal
    import sys

    from .recycle import RECYCLE_EXIT_CODE
    flightrec.init_from_env(role="sync-front")
    capture.init_from_env()
    slo.init_from_env()
    port = knobs.get_int("LISTEN_PORT") or 0
    metrics_port = knobs.get_int("PROMETHEUS_PORT") or 0
    httpd, metricsd, svc = make_server(port, metrics_port)
    _recycle_watch_thread(svc, httpd)
    # co-located callers can skip HTTP entirely: length-prefixed frames
    # over a unix socket, same batch contract, byte-identical responses
    uds = None
    uds_path = knobs.get_str("LDT_UNIX_SOCKET")
    if uds_path:
        uds = wire.UnixFrameServer(svc, uds_path)
        uds.start()
        print(json.dumps({"msg": f"unix-socket lane on {uds_path}"}),
              flush=True)
    # shared-memory ring lane: co-located heavy producers mmap frames
    # in, the scan thread parses them in place (service/shmring.py)
    shm = None
    shm_dir = knobs.get_str("LDT_SHM_DIR")
    if shm_dir:
        from . import shmring
        shm = shmring.ShmRingServer(svc, shm_dir)
        shm.start()
        print(json.dumps({"msg": f"shm ring lane on {shm_dir}"}),
              flush=True)
    threading.Thread(target=metricsd.serve_forever, daemon=True).start()
    # report the BOUND ports (port 0 picks ephemerals — supervised and
    # test runs parse this line)
    print(json.dumps({"msg": "language-detector listening on "
                             f":{httpd.server_address[1]}, metrics on "
                             f":{metricsd.server_address[1]}"}),
          flush=True)
    # warmup (LDT_WARMUP) + readiness handshake (LDT_READY_FILE /
    # LDT_SWAPPED): the standby contract with the supervisor's swap
    # drill, off the serving threads
    from .swap import startup_ready_task
    threading.Thread(target=startup_ready_task,
                     args=(svc, (httpd.server_address[1],
                                 metricsd.server_address[1])),
                     daemon=True, name="ldt-warmup").start()

    def _on_term(signum, frame):
        # graceful drain (the supervisor's swap cutover, docker stop):
        # stop accepting, flush in-flight, exit 0. shutdown() blocks
        # until serve_forever returns, and this handler RUNS inside
        # serve_forever's thread — a direct call would deadlock
        if not getattr(httpd, "_ldt_drain", False):
            httpd._ldt_drain = True
            print(json.dumps({"msg": "draining worker: SIGTERM"}),
                  flush=True)
            threading.Thread(target=httpd.shutdown,
                             daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        pass  # embedded in a non-main thread (tests)
    from .. import profiling
    profiling.install_sigusr2()
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        flightrec.emit_event("proc_exit", role="sync-front")
        planned = getattr(httpd, "_ldt_recycle", False) or \
            getattr(httpd, "_ldt_drain", False)
        drain_sec = knobs.get_float("LDT_RECYCLE_DRAIN_SEC") or 5.0
        if uds is not None:
            # same drain contract as the HTTP accept loop: stop taking
            # frames, let in-flight ones answer before the batcher closes
            uds.close(drain_sec=drain_sec if planned else 0.0)
        if shm is not None:
            shm.close(drain_sec=drain_sec if planned else 0.0)
        if planned:
            # shutdown() only stops the accept loop: wait for in-flight
            # handler threads (a full-size flush mid-request must
            # survive a planned recycle / swap cutover) up to the drain
            # bound before the batcher closes under them
            deadline = time.monotonic() + drain_sec
            while svc.http_inflight() > 0 and \
                    time.monotonic() < deadline:
                time.sleep(0.05)
        svc.batcher.close()
    if getattr(httpd, "_ldt_recycle", False):
        sys.exit(RECYCLE_EXIT_CODE)


if __name__ == "__main__":
    main()
