"""Unit suite for the fault-tolerant device-pool scheduler
(parallel/pool.py): rotation, lost-batch failover, exactly-once
resolution, the no_retry/deadline contract, typed exhaustion, lane
health (EWMA/p95/eviction), knob-driven construction, the batcher's
flush-worker widening, and pool-on/pool-off engine equivalence.

Scheduler tests drive DevicePool directly with stub lanes and stub
device futures (constructor-injected config + clock, no env), so every
state transition is deterministic; the HTTP-level chaos lives in
test_faults.py."""
from __future__ import annotations

import numpy as np
import pytest

from language_detector_tpu import native, telemetry
from language_detector_tpu.parallel import pool as pool_mod
from language_detector_tpu.parallel.pool import (DevicePool, Lane,
                                                 PoolExhausted)
from language_detector_tpu.service import batcher as batcher_mod

needs_native = pytest.mark.skipif(not native.available(),
                                  reason="native packer unavailable")


class _Raw:
    """Stub device future: __array__ delegates to a callable (the
    shape of a jax async result)."""

    def __init__(self, fn):
        self._fn = fn

    def __array__(self, dtype=None):
        out = np.asarray(self._fn())
        return out if dtype is None else out.astype(dtype)


def _pool(n_lanes=2, **kw):
    kw.setdefault("hedge_factor", 0)
    kw.setdefault("evict_failures", 3)
    kw.setdefault("probe_cooldown_sec", 60.0)
    kw.setdefault("max_redispatch", 4)
    return DevicePool([Lane(i, None) for i in range(n_lanes)], **kw)


def _counter(name, **labels):
    return telemetry.REGISTRY.counter_value(name, **labels)


# -- rotation & dispatch ------------------------------------------------------


def test_round_robin_rotation():
    pool = _pool(3)
    try:
        used: list = []
        for _ in range(6):
            pf = pool.launch(lambda lane: _Raw(lambda: [0]))
            used.append(pf.lane.idx)
        assert used == [0, 1, 2, 0, 1, 2]
    finally:
        pool.close()


def test_launch_error_fails_over_to_next_lane():
    pool = _pool(2)
    try:
        calls: list = []

        def launch_fn(lane):
            calls.append(lane.idx)
            if lane.idx == 0:
                raise RuntimeError("device lost at dispatch")
            return _Raw(lambda: np.array([7]))

        pf = pool.launch(launch_fn)
        assert calls == [0, 1]
        assert pf.lane.idx == 1
        assert np.asarray(pf).tolist() == [7]
        # the failed dispatch fed lane 0's health
        assert pool.lanes[0].snapshot()["consecutive_failures"] == 1
        assert pool.lanes[1].snapshot()["consecutive_failures"] == 0
    finally:
        pool.close()


def test_fetch_error_fails_over_and_counts():
    pool = _pool(2)
    try:
        boom = _Raw(lambda: (_ for _ in ()).throw(
            RuntimeError("fetch died")))
        good = _Raw(lambda: np.array([1, 2]))
        raws = {0: boom, 1: good}
        fo0 = _counter("ldt_pool_failover_total")
        pf = pool.launch(lambda lane: raws[lane.idx])
        assert np.asarray(pf).tolist() == [1, 2]
        assert _counter("ldt_pool_failover_total") == fo0 + 1
        assert pool.lanes[0].snapshot()["consecutive_failures"] == 1
    finally:
        pool.close()


def test_result_memoized_fetch_runs_exactly_once():
    pool = _pool(2)
    try:
        fetches = [0]

        def fn():
            fetches[0] += 1
            return np.array([3.0])

        pf = pool.launch(lambda lane: _Raw(fn))
        a = np.asarray(pf)
        b = np.asarray(pf)
        assert a.tolist() == b.tolist() == [3.0]
        assert fetches[0] == 1  # never re-fetched, never re-dispatched
    finally:
        pool.close()


# -- the no_retry / deadline contract -----------------------------------------


class _Deadline:
    def __init__(self, expired):
        self._expired = expired

    def expired(self):
        return self._expired


def test_no_retry_trace_blocks_failover():
    pool = _pool(2)
    try:
        tr = telemetry.Trace()
        tr.no_retry = True
        boom = _Raw(lambda: (_ for _ in ()).throw(
            RuntimeError("fetch died")))
        fo0 = _counter("ldt_pool_failover_total")
        pf = pool.launch(lambda lane: boom, trace=tr)
        with pytest.raises(RuntimeError, match="fetch died"):
            np.asarray(pf)
        assert _counter("ldt_pool_failover_total") == fo0
    finally:
        pool.close()


def test_expired_deadline_blocks_failover():
    pool = _pool(2)
    try:
        tr = telemetry.Trace()
        tr.deadline = _Deadline(expired=True)
        boom = _Raw(lambda: (_ for _ in ()).throw(
            RuntimeError("fetch died")))
        fo0 = _counter("ldt_pool_failover_total")
        pf = pool.launch(lambda lane: boom, trace=tr)
        with pytest.raises(RuntimeError, match="fetch died"):
            np.asarray(pf)
        assert _counter("ldt_pool_failover_total") == fo0
        # a live deadline keeps the failover path open
        tr2 = telemetry.Trace()
        tr2.deadline = _Deadline(expired=False)
        raws = {0: boom, 1: _Raw(lambda: np.array([5]))}
        pf = pool.launch(lambda lane: raws[lane.idx], trace=tr2)
        assert np.asarray(pf).tolist() == [5]
    finally:
        pool.close()


def test_exhausted_budget_raises_typed_with_cause():
    pool = _pool(2, max_redispatch=3)
    try:
        boom = _Raw(lambda: (_ for _ in ()).throw(
            RuntimeError("every lane dead")))
        pf = pool.launch(lambda lane: boom)
        with pytest.raises(PoolExhausted) as ei:
            np.asarray(pf)
        assert "budget 3" in str(ei.value)
        assert isinstance(ei.value.__cause__, RuntimeError)
    finally:
        pool.close()


# -- lane health --------------------------------------------------------------


def test_lane_ewma_and_p95():
    lane = Lane(0, None)
    assert lane.p95_ms() is None  # below the trust floor
    for ms in (10.0, 10.0, 10.0, 10.0, 50.0):
        lane.record_success(ms, 0.0)
    assert lane.p95_ms() == 50.0
    snap = lane.snapshot()
    assert snap["dispatches"] == 5
    assert 10.0 < snap["ewma_ms"] < 50.0


def test_lane_eviction_probe_cycle_with_fake_clock():
    lane = Lane(0, None)
    assert lane.record_failure(0.0, evict_after=2) is False
    assert lane.record_failure(0.0, evict_after=2) is True  # evicted
    assert lane.state() == pool_mod.LANE_EVICTED
    # cooldown not elapsed: no probe yet
    assert lane.try_begin_probe(3.0, cooldown_sec=5.0) is False
    assert lane.try_begin_probe(6.0, cooldown_sec=5.0) is True
    assert lane.state() == pool_mod.LANE_PROBING
    # a failed probe re-evicts WITHOUT recounting the eviction
    assert lane.record_failure(6.0, evict_after=2) is False
    assert lane.state() == pool_mod.LANE_EVICTED
    # ...and a successful probe re-admits
    assert lane.try_begin_probe(12.0, cooldown_sec=5.0) is True
    assert lane.record_success(4.0, 12.0) is True
    assert lane.state() == pool_mod.LANE_ACTIVE


def test_capacity_load_scale():
    pool = _pool(4, evict_failures=1)
    try:
        assert pool.capacity() == (4, 4)
        assert pool.capacity_load() == 0.0
        pool.lanes[0].record_failure(0.0, 1)
        pool.lanes[1].record_failure(0.0, 1)
        assert pool.capacity() == (2, 4)
        assert pool.capacity_load() == pytest.approx(0.6)
        pool.lanes[2].record_failure(0.0, 1)
        pool.lanes[3].record_failure(0.0, 1)
        assert pool.capacity_load() == pytest.approx(1.2)
    finally:
        pool.close()


def test_fully_evicted_pool_still_dispatches():
    """All lanes out of rotation: work is drafted onto an evicted lane
    anyway (errors must surface typed upstream, not queue forever)."""
    pool = _pool(2, evict_failures=1, probe_cooldown_sec=600.0)
    try:
        for ln in pool.lanes:
            ln.record_failure(0.0, 1)
        pf = pool.launch(lambda lane: _Raw(lambda: np.array([9])))
        assert np.asarray(pf).tolist() == [9]
    finally:
        pool.close()


def test_stats_shape():
    pool = _pool(2)
    try:
        s = pool.stats()
        assert s["lanes_total"] == 2 and s["lanes_active"] == 2
        assert s["lane_mesh_size"] == 1
        assert [ln["lane"] for ln in s["lanes"]] == ["lane0", "lane1"]
        assert all(ln["state"] == "active" for ln in s["lanes"])
    finally:
        pool.close()


# -- knob-driven construction & service wiring --------------------------------


def test_build_from_env_off_by_default(monkeypatch):
    monkeypatch.delenv("LDT_POOL_LANES", raising=False)
    assert pool_mod.build_from_env(lambda *a: None) is None
    monkeypatch.setenv("LDT_POOL_LANES", "0")
    assert pool_mod.build_from_env(lambda *a: None) is None


def test_build_from_env_simulated_lanes(monkeypatch):
    monkeypatch.setenv("LDT_POOL_LANES", "3")
    monkeypatch.setenv("LDT_POOL_MAX_REDISPATCH", "5")

    def score(dt, wire):
        return None

    pool = pool_mod.build_from_env(score)
    try:
        assert pool is not None
        assert len(pool.lanes) == 3
        assert all(ln.score_fn is score for ln in pool.lanes)
        assert pool.lane_mesh_size == 1
        assert pool.max_redispatch == 5
    finally:
        pool.close()


def test_flush_workers_widen_with_pool(monkeypatch):
    monkeypatch.delenv("LDT_POOL_LANES", raising=False)
    base = batcher_mod.flush_workers()
    assert base == batcher_mod._FLUSH_WORKERS
    monkeypatch.setenv("LDT_POOL_LANES", "8")
    # enough flush workers to keep every lane fed plus one spare
    assert batcher_mod.flush_workers() == max(base, 9)


# -- engine equivalence (pool on == pool off) ---------------------------------


@needs_native
def test_engine_pool_answers_identical(monkeypatch):
    """The acceptance invariant behind the default: a pooled engine
    (simulated lanes, no faults) answers byte-identically to the
    pool-off engine, and the pool-off engine has pool=None."""
    from language_detector_tpu.models.ngram import NgramBatchEngine
    docs = [f"the quick brown fox jumps over the lazy dog equivalence "
            f"check number {i}" for i in range(80)]

    monkeypatch.delenv("LDT_POOL_LANES", raising=False)
    plain = NgramBatchEngine()
    assert plain.pool is None
    want = plain.detect_codes(docs)

    monkeypatch.setenv("LDT_POOL_LANES", "2")
    pooled = NgramBatchEngine()
    try:
        assert pooled.pool is not None
        assert len(pooled.pool.lanes) == 2
        assert pooled.detect_codes(docs) == want
        assert pooled.pool.stats()["lanes_active"] == 2
    finally:
        pooled.pool.close()
