"""SLO-driven autotuner over the declared mutable-knob space.

The runtime config plane (configplane.py) makes a declared subset of
knobs settable at runtime; this module SEARCHES that space. Given an
``evaluate`` callback that runs one candidate override batch against
replayed or synthetic traffic (bench.py wires the real thing: push
the batch through the fleet's POST /configz, replay a loadgen
scenario, read back the SLIs), coordinate descent walks one knob at a
time through multiplier moves clamped to the registry's declared
mrange — plus "off" for bound-style knobs and a geometric seed ladder
for knobs starting unset — keeping a move only when it scores better.

Scoring is feasibility-first against the declared LDT_SLO targets: a
candidate that violates the latency or error-budget target pays a
penalty proportional to the overshoot that dwarfs any throughput win,
so the search first finds the feasible region and only then maximizes
the docs/sec cost proxy inside it. The score is deliberately the same
shape the SLO engine alerts on — what the autotuner optimizes is what
the burn-rate alert measures.

Offline by construction: everything here is pure policy driven
through the injectable ``evaluate``; tests search synthetic response
surfaces with zero servers. The only side effects are the
ldt_autotune_* counters.
"""
from __future__ import annotations

import logging

from . import knobs as _knobs
from . import slo as _slo
from . import telemetry

_log = logging.getLogger(__name__)

# score penalty per unit of relative SLO overshoot: must dwarf any
# achievable docs/sec so feasibility always dominates throughput
PENALTY = 1e6

# multiplier moves for a knob that currently holds a value
MOVES = (0.25, 0.5, 2.0, 4.0)

# rungs of the geometric seed ladder for a knob starting unset/off
SEED_RUNGS = 4


def knob_space(names=None) -> list:
    """The searchable surface: (name, lo, hi, is_bound) per declared
    mutable scalar knob, optionally restricted to `names`."""
    out = []
    for k in _knobs.mutable_knobs():
        if k.ktype not in ("int", "float") or k.mrange is None:
            continue
        if names is not None and k.name not in names:
            continue
        lo, hi = k.mrange
        out.append((k.name, float(lo), float(hi), k.bound))
    return out


def _clamp(knob_name: str, v: float, lo: float, hi: float):
    v = min(max(v, lo), hi)
    if _knobs.KNOBS[knob_name].ktype == "int":
        return int(round(v))
    return v


def candidates(name: str, current, lo: float, hi: float,
               is_bound: bool) -> list:
    """Candidate values for one knob: multiplier moves around a live
    value, a geometric ladder across the range for an unset one, and
    None ("off") for bound-style knobs where non-positive means
    disabled."""
    cands: list = []
    if current is None:
        # seed the search across the declared range geometrically
        for i in range(1, SEED_RUNGS + 1):
            frac = i / (SEED_RUNGS + 1)
            v = _clamp(name, lo * (hi / max(lo, 1e-9)) ** frac, lo, hi)
            if v not in cands:
                cands.append(v)
    else:
        for m in MOVES:
            v = _clamp(name, float(current) * m, lo, hi)
            if v != current and v not in cands:
                cands.append(v)
        if is_bound:
            cands.append(None)  # try turning the bound off
    return cands


def score(metrics: dict, spec) -> float:
    """Feasibility-first scalar score for one evaluated candidate.

    `metrics` carries the replay SLIs: p99_ms, err_pct and the
    docs/sec cost proxy ok_docs_per_sec. `spec` is the parsed LDT_SLO
    declaration (slo.parse_spec); None scores throughput only."""
    s = float(metrics.get("ok_docs_per_sec", 0.0))
    if spec is None:
        return s
    target = spec.target_ms
    if target is not None and target > 0:
        p99 = float(metrics.get("p99_ms", 0.0))
        if p99 > target:
            s -= PENALTY * (p99 / target - 1.0 + 1.0)
    budget = spec.err_pct
    if budget is not None and budget > 0:
        err = float(metrics.get("err_pct", 0.0))
        if err > budget:
            s -= PENALTY * (err / budget - 1.0 + 1.0)
    return s


def autotune(evaluate, names=None, rounds: int = 2,
             spec=None) -> dict:
    """Coordinate descent over the mutable-knob space.

    evaluate(overrides: dict) -> metrics dict (p99_ms, err_pct,
    ok_docs_per_sec, ...). Starts from the current effective values
    (env + any live overrides), walks each knob's candidates in
    declaration order, keeps improvements, and stops early when a
    full round changes nothing. Returns the winning override batch
    with its metrics, plus the baseline's, for the BENCH_replay.json
    round."""
    if spec is None:
        spec = _slo.parse_spec(_knobs.get_str("LDT_SLO"))
    space = knob_space(names)
    current = {name: _knobs.value(name) for name, *_rest in space}
    overrides: dict = {}
    cache: dict = {}

    def run(ov: dict) -> dict:
        key = tuple(sorted((k, v) for k, v in ov.items()
                           if v is not None))
        if key not in cache:
            telemetry.REGISTRY.counter_inc("ldt_autotune_evals_total",
                                           1)
            cache[key] = evaluate(dict(ov))
        return cache[key]

    baseline = run(overrides)
    best_score = score(baseline, spec)
    best_metrics = baseline
    _log.info("autotune: baseline score %.2f (%s)", best_score,
              baseline)
    for rnd in range(rounds):
        telemetry.REGISTRY.counter_inc("ldt_autotune_rounds_total", 1)
        improved = False
        for name, lo, hi, is_bound in space:
            held = overrides.get(name, current[name])
            for cand in candidates(name, held, lo, hi, is_bound):
                trial = dict(overrides)
                if cand is None:
                    trial.pop(name, None)
                else:
                    trial[name] = cand
                m = run(trial)
                sc = score(m, spec)
                if sc > best_score:
                    best_score = sc
                    best_metrics = m
                    overrides = trial
                    improved = True
                    _log.info("autotune: %s=%s scores %.2f", name,
                              cand, sc)
        if not improved:
            break
    return {
        "best": {k: v for k, v in sorted(overrides.items())},
        "best_score": round(best_score, 4),
        "best_metrics": best_metrics,
        "baseline_metrics": baseline,
        "baseline_score": round(score(baseline, spec), 4),
        "evals": len(cache),
        "spec": spec.as_dict() if spec is not None else None,
    }
