"""Fleet supervisor: N supervised worker generations behind one port.

`service/supervisor.py` manages exactly one worker (plus a SIGHUP
standby); this module generalizes the same policies to a fleet of
`LDT_FLEET_WORKERS` members that share the listen port via the
SO_REUSEPORT path the swap drill already requires (the fleet forces
LDT_REUSEPORT=1 into every member env) — turning a single-worker box
into a many-core front tier a load balancer can sit on
(docs/ROBUSTNESS.md, "Fleet supervisor").

Per member, the fleet keeps the single-worker contracts intact:

  - its own generation number, ready-file handshake
    (service/swap.startup_ready_task), shared compile cache, and an
    exactly-once stop latch (supervisor._forward_stop);
  - crash backoff with jitter and a per-member crash-loop detector
    (LDT_CRASH_LOOP_MAX crashes in LDT_CRASH_LOOP_WINDOW_SEC parks the
    member instead of restarting it forever);
  - a per-member unix socket (`LDT_UNIX_SOCKET` + ".<slot>") and a
    per-member metrics port, recovered from the ready-file JSON when
    the operator binds port 0.

On top sits the fleet control plane, modeled on the device pool
(parallel/pool.py):

  - member health states SPAWNING -> READY -> DEGRADED -> DEAD ->
    RESTARTING (declared in tools/lint/fsm_registry.py, machine
    "fleet-member"), driven by the ready-file handshake plus periodic
    /debug/vars scrapes (queue depth, brownout level, readiness);
  - a fleet-wide crash circuit (machine "fleet-circuit"): the same
    LDT_CRASH_LOOP_MAX/_WINDOW_SEC counted across ALL members, OR a
    bootstrapped fleet losing its last accepting member, opens the
    circuit — restarts stop (no N-way restart storm; surviving members
    and worker-level brownout/breaker provide the scalar/503 posture)
    until a cooldown admits exactly one half-open probe member whose
    readiness closes the circuit and re-arms restarts;
  - autoscale between LDT_FLEET_MIN/MAX on sustained admission queue
    depth and brownout level with hold-time hysteresis; scale-down
    drains the victim through the ordinary SIGTERM path (stop
    accepting, flush in-flight, exit 0), so shrink is zero-drop;
  - SIGHUP runs the blue/green drill as a ROLLING swap: one warmed
    standby at a time, each roll preconditioned on every other member
    being READY, so the fleet never drops below N-1 ready workers.

The bounded model checker (tools/lint/model_check.py, product
"fleet-control") drives the real FleetMember/FleetControl classes over
every crash/ready/probe interleaving and proves the headline
invariant: while the fleet is nominally up (bootstrapped, circuit
closed) at least one member is accepting.

Run: the classic entry point dispatches here —
     LDT_FLEET_WORKERS=3 python -m language_detector_tpu.service.supervisor
"""
from __future__ import annotations

import http.server
import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

from .. import faults, flightrec, knobs, telemetry
from ..locks import make_lock
from .recycle import RECYCLE_EXIT_CODE
from .supervisor import _forward_stop, _log

# Member lifecycle states, declared in tools/lint/fsm_registry.py
# (machine "fleet-member"): FleetMember.state only moves through the
# guarded mark_* methods below, so the conformance pass proves every
# write against the declared table.
FLEET_SPAWNING = 0    # process launched, ready handshake pending
FLEET_READY = 1       # ready file landed / health scrape passing
FLEET_DEGRADED = 2    # consecutive health-scrape failures
FLEET_DEAD = 3        # process exited (crash, recycle, drain)
FLEET_RESTARTING = 4  # respawn decided, Popen not issued yet

STATE_NAMES = {FLEET_SPAWNING: "spawning", FLEET_READY: "ready",
               FLEET_DEGRADED: "degraded", FLEET_DEAD: "dead",
               FLEET_RESTARTING: "restarting"}

# Fleet crash-circuit states (machine "fleet-circuit"): open means
# "stop respawning members", not "stop serving" — survivors keep
# serving and worker-level admission provides the 429/503 posture.
CIRCUIT_CLOSED = 0  # restarts allowed
CIRCUIT_OPEN = 1    # correlated crash: restarts parked until cooldown
CIRCUIT_PROBE = 2   # one half-open probe member spawning

CIRCUIT_NAMES = {CIRCUIT_CLOSED: "closed", CIRCUIT_OPEN: "open",
                 CIRCUIT_PROBE: "probe"}


class FleetMember:
    """One supervised worker slot. The object persists across respawns
    (state, crash history, and backoff are per-slot, not per-process).

    Deliberately lock-free: every field is owned by the fleet main
    loop — the status thread reads only the immutable snapshots
    FleetStatus holds (same confinement argument as admission's
    FairScheduler)."""

    def __init__(self, slot: int):
        self.slot = slot
        self.state = FLEET_SPAWNING
        self.proc: subprocess.Popen | None = None
        self.signaled: subprocess.Popen | None = None  # stop latch arg
        self.generation = 0
        self.ready_file = ""
        self.metrics_port = 0
        self.ready_deadline = 0.0
        self.last_scrape = 0.0
        self.fail_streak = 0
        self.queue_docs = 0
        self.brownout = 0
        self.config_generation = 0  # from the member's /debug/vars
        self.crash_times: list = []
        self.consec_crashes = 0
        self.next_spawn_at = 0.0
        self.parked = False     # per-member crash loop: stop respawning
        self.retiring = False   # scale-down drain in progress

    # -- guarded FSM writes (one declared transition per branch) ------

    def mark_ready(self) -> None:
        if self.state == FLEET_SPAWNING:
            self.state = FLEET_READY
        elif self.state == FLEET_DEGRADED:
            self.state = FLEET_READY

    def mark_degraded(self) -> None:
        if self.state == FLEET_READY:
            self.state = FLEET_DEGRADED

    def mark_dead(self) -> None:
        if self.state == FLEET_SPAWNING:
            self.state = FLEET_DEAD
        elif self.state == FLEET_READY:
            self.state = FLEET_DEAD
        elif self.state == FLEET_DEGRADED:
            self.state = FLEET_DEAD

    def mark_restarting(self) -> None:
        if self.state == FLEET_DEAD:
            self.state = FLEET_RESTARTING

    def mark_spawning(self) -> None:
        if self.state == FLEET_RESTARTING:
            self.state = FLEET_SPAWNING

    def accepting(self) -> bool:
        """A member whose process is up with a bound listener: READY,
        or DEGRADED (scrapes failing but the socket still answers —
        eviction happens by death, not by flapping health)."""
        return self.state == FLEET_READY or self.state == FLEET_DEGRADED


class FleetControl:
    """Fleet-wide crash circuit + autoscale hysteresis. Pure policy —
    no I/O, injectable clock — so the bounded model checker can drive
    it composed with FleetMember (product "fleet-control").

    Main-loop confined like FleetMember: no locks."""

    def __init__(self, loop_max: int, loop_window: float,
                 cooldown_sec: float, scale_hold_sec: float,
                 up_depth: int, down_depth: int):
        self.loop_max = loop_max
        self.loop_window = loop_window
        self.cooldown_sec = cooldown_sec
        self.scale_hold_sec = scale_hold_sec
        self.up_depth = up_depth
        self.down_depth = down_depth
        self.circuit = CIRCUIT_CLOSED
        self.crash_times: list = []
        self.opened_at = 0.0
        self.bootstrapped = False  # a member has been READY at least once
        self._over_since: float | None = None
        self._idle_since: float | None = None

    # -- crash circuit ------------------------------------------------

    def record_crash(self, now: float, accepting: int) -> bool:
        """Account one member crash. Trips the circuit (returns True)
        on a correlated crash: LDT_CRASH_LOOP_MAX crashes across the
        fleet inside the window, OR a bootstrapped fleet left with
        zero accepting members — by definition every member failed
        together, and N independent restart storms would hide it."""
        self.crash_times = [t for t in self.crash_times
                            if now - t <= self.loop_window]
        self.crash_times.append(now)
        correlated = len(self.crash_times) >= self.loop_max
        wipeout = self.bootstrapped and accepting == 0
        if (correlated or wipeout) and self.circuit == CIRCUIT_CLOSED:
            self.circuit = CIRCUIT_OPEN
            self.opened_at = now
            return True
        return False

    def probe_due(self, now: float) -> bool:
        return (self.circuit == CIRCUIT_OPEN
                and now - self.opened_at >= self.cooldown_sec)

    def begin_probe(self) -> None:
        if self.circuit == CIRCUIT_OPEN:
            self.circuit = CIRCUIT_PROBE

    def probe_ok(self) -> None:
        """A probe member reached READY (or capacity was still there):
        close the circuit and forget the crash history — the next
        correlated crash must re-accumulate its own evidence."""
        if self.circuit == CIRCUIT_PROBE:
            self.circuit = CIRCUIT_CLOSED
            self.crash_times = []

    def probe_failed(self, now: float) -> None:
        if self.circuit == CIRCUIT_PROBE:
            self.circuit = CIRCUIT_OPEN
            self.opened_at = now

    # -- autoscale hysteresis -----------------------------------------

    def scale_delta(self, now: float, depth: int, brownout: int) -> int:
        """+1 / -1 / 0: the overload (queue depth >= up_depth, or
        brownout >= 2) or idle (depth <= down_depth and no brownout)
        condition must HOLD for scale_hold_sec before a step fires,
        and firing re-arms the timer — one step per held window, never
        a flap per sample."""
        overloaded = depth >= self.up_depth or brownout >= 2
        idle = depth <= self.down_depth and brownout == 0
        if overloaded:
            self._idle_since = None
            if self._over_since is None:
                self._over_since = now
            elif now - self._over_since >= self.scale_hold_sec:
                self._over_since = None
                return 1
        elif idle:
            self._over_since = None
            if self._idle_since is None:
                self._idle_since = now
            elif now - self._idle_since >= self.scale_hold_sec:
                self._idle_since = None
                return -1
        else:
            self._over_since = None
            self._idle_since = None
        return 0


class FleetStatus:
    """Snapshot shared between the fleet main loop (writer) and the
    status endpoint thread (reader)."""

    def __init__(self):
        self._lock = make_lock("fleet.status")
        self._snap: dict = {"members": [], "desired": 0, "ready": 0,
                            "circuit": "closed"}

    def update(self, snap: dict) -> None:
        with self._lock:
            self._snap = snap

    def read(self) -> dict:
        with self._lock:
            return self._snap


class FleetConfig:
    """The fleet-committed runtime-config batch: the result of the
    last canary-proven POST /configz push. Written by the status-server
    thread, read by the main loop's heal pass (which re-pushes it onto
    respawned or fan-out-missed members), so a SIGKILLed member cannot
    leave the fleet split-brained on config generation."""

    def __init__(self):
        self._lock = make_lock("fleet.config")
        # serializes whole canary-push campaigns (non-blocking acquire:
        # a second concurrent POST /configz answers 409, mirroring the
        # per-member probation-in-flight refusal)
        self.push_lock = make_lock("fleet.config.push")
        self.generation = 0
        self.values: dict = {}

    def next_generation(self) -> int:
        with self._lock:
            return self.generation + 1

    def commit(self, generation: int, values: dict) -> None:
        with self._lock:
            if generation > self.generation:
                self.generation = generation
                self.values = dict(values)

    def read(self) -> tuple:
        with self._lock:
            return self.generation, dict(self.values)


def _member_configz(port: int, payload: dict | None = None,
                    timeout: float = 2.0) -> tuple:
    """POST (payload given) or GET one member's /configz. Returns
    (status, body dict); a 4xx refusal still carries the member's JSON
    body, so callers can surface the member's own error."""
    url = f"http://127.0.0.1:{port}/configz"
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode() or "{}")
        except ValueError:
            return e.code, {}


def _fleet_config_push(snap: dict, fleet_config: FleetConfig,
                       body: bytes) -> tuple:
    """The supervisor's guarded fleet-wide config push: canary first,
    fan out only after the canary survives probation.

    1. Stage the batch on ONE ready member (the canary) with the
       requested probation window, so every other member — at least
       N-1 of the fleet — keeps serving on the old config.
    2. Poll the canary's GET /configz (each poll drives its probation
       tick) until it reports committed or rolled_back.
    3. Only on commit: fan the batch out to the rest of the fleet with
       probation 0 and the SAME generation stamp, record it as the
       fleet-committed config (the main loop heals any member the
       fan-out missed), and apply it to the supervisor's own process so
       fleet-scoped knobs (autoscale thresholds) go live too.

    Returns (http status, response dict) for the status server."""
    try:
        req = json.loads(body or b"{}")
        if not isinstance(req, dict):
            raise ValueError("body must be a JSON object")
        updates = req.get("set")
        if not isinstance(updates, dict) or not updates:
            raise ValueError('body must carry a non-empty "set" object')
        probation = req.get("probation_sec")
        probation = float(probation) if probation is not None else (
            knobs.get_float("LDT_CONFIG_PROBATION_SEC") or 0.0)
    except (ValueError, json.JSONDecodeError) as e:
        return 400, {"error": f"bad /configz request: {e}"}
    ports = [int(m.get("metrics_port") or 0)
             for m in snap.get("members", ())
             if m.get("state") in ("ready", "degraded")
             and int(m.get("metrics_port") or 0) > 0]
    if not ports:
        return 503, {"error": "no ready member to canary the config on"}
    if not fleet_config.push_lock.acquire(blocking=False):
        return 409, {"error": "a fleet config push is already in flight"}
    try:
        generation = fleet_config.next_generation()
        canary, rest = ports[0], ports[1:]
        try:
            st, result = _member_configz(
                canary, {"set": updates, "probation_sec": probation,
                         "generation": generation})
        except Exception as e:  # noqa: BLE001 - surface, don't crash
            return 503, {"error": f"canary push failed: {e!r}"}
        if st != 200:
            return st, {"error": "canary refused the config",
                        "canary": result}
        deadline = time.time() + probation + 10.0
        while True:
            state = (result or {}).get("state")
            if state == "committed" \
                    and result.get("generation") == generation:
                break
            if state == "rolled_back" \
                    and result.get("staged_generation") == generation:
                flightrec.emit_event("config_rolled_back",
                                     generation=generation,
                                     reason="canary rolled back")
                return 409, {"error": "canary rolled the config back",
                             "generation": generation,
                             "canary": result}
            if time.time() >= deadline:
                return 504, {"error": "canary probation did not "
                                      "resolve in time",
                             "generation": generation,
                             "canary": result}
            time.sleep(0.2)
            try:
                _, result = _member_configz(canary)
            except Exception:  # canary mid-restart: keep polling
                pass
        fanout = {"set": updates, "probation_sec": 0,
                  "generation": generation}
        pushed, heal_pending = [canary], []
        for port in rest:
            try:
                st, _r = _member_configz(port, fanout)
            except Exception:  # noqa: BLE001 - heal pass converges it
                st = 0
            (pushed if st == 200 else heal_pending).append(port)
        fleet_config.commit(generation, updates)
        try:
            # the supervisor's own process: autoscale knobs go live
            knobs.apply_overrides(updates)
        except ValueError as e:
            _log("fleet: committed batch refused by supervisor's own "
                 "registry", reason="config-push", error=repr(e))
        telemetry.REGISTRY.counter_inc("ldt_config_applies_total",
                                       result="committed")
        flightrec.emit_event("config_committed", generation=generation)
        _log("fleet: config push committed", reason="config-push",
             generation=generation, canary_port=canary,
             pushed=len(pushed), heal_pending=len(heal_pending))
        return 200, {"generation": generation, "values": updates,
                     "probation_sec": probation, "canary_port": canary,
                     "pushed": pushed, "heal_pending": heal_pending,
                     "canary": result}
    finally:
        fleet_config.push_lock.release()


def _fleet_families(snap: dict) -> list:
    """Gauge families for the fleet control plane, rendered from the
    latest snapshot (counters come from the process registry)."""
    circuit_num = {"closed": 0, "open": 1, "probe": 2}.get(
        snap.get("circuit", "closed"), 0)
    return [
        telemetry.metric_family(
            "ldt_fleet_desired",
            [("ldt_fleet_desired", None, snap.get("desired", 0))]),
        telemetry.metric_family(
            "ldt_fleet_ready",
            [("ldt_fleet_ready", None, snap.get("ready", 0))]),
        telemetry.metric_family(
            "ldt_fleet_members",
            [("ldt_fleet_members", None,
              len(snap.get("members", ())))]),
        telemetry.metric_family(
            "ldt_fleet_circuit_state",
            [("ldt_fleet_circuit_state", None, circuit_num)]),
    ]


def _member_slow_traces(metrics_port: int) -> list:
    """One member's /debug/slow ring, [] when the scrape fails (a dead
    or mid-restart member must not fail the whole merge)."""
    try:
        url = f"http://127.0.0.1:{metrics_port}/debug/slow"
        with urllib.request.urlopen(url, timeout=2.0) as r:
            return json.loads(r.read().decode()).get("traces") or []
    except Exception:  # noqa: BLE001 - merge is best-effort per member
        return []


def _member_sloz(metrics_port: int) -> "dict | None":
    """One member's /sloz document, None when the scrape fails (a dead
    or mid-restart member must not fail the fleet merge)."""
    try:
        url = f"http://127.0.0.1:{metrics_port}/sloz"
        with urllib.request.urlopen(url, timeout=2.0) as r:
            return json.loads(r.read().decode())
    except Exception:  # noqa: BLE001 - merge is best-effort per member
        return None


def _fleet_slo(snap: dict) -> dict:
    """The fleet-scoped SLO merge for /fleetz and /sloz: every live
    member's /sloz joined; per-tenant SLIs aggregate as summed counts
    and WORST (max) burn rate across members — one hot member breaching
    a tenant's budget is a breach, averaging would hide it."""
    members: list = []
    tenants: dict = {}
    alert = False
    enabled = False
    spec = None
    for mem in snap.get("members", ()):
        port = int(mem.get("metrics_port") or 0)
        if port <= 0:
            continue
        sz = _member_sloz(port)
        if not sz:
            continue
        members.append({"slot": mem.get("slot"),
                        "pid": mem.get("pid"), "sloz": sz})
        if not sz.get("enabled"):
            continue
        enabled = True
        spec = spec or sz.get("spec")
        if (sz.get("alert") or {}).get("state") == "breach":
            alert = True
        for tenant, view in (sz.get("tenants") or {}).items():
            fast = view.get("fast") or {}
            agg = tenants.setdefault(
                tenant, {"count": 0, "bad": 0, "shed": 0,
                         "burn_rate_max": 0.0, "members": 0})
            agg["count"] += fast.get("count", 0)
            agg["bad"] += fast.get("bad", 0)
            agg["shed"] += fast.get("shed", 0)
            agg["burn_rate_max"] = max(agg["burn_rate_max"],
                                       fast.get("burn_rate", 0.0))
            agg["members"] += 1
    return {"enabled": enabled, "spec": spec,
            "alert": "breach" if alert else "ok",
            "tenants": tenants, "members": members}


def _fleet_traces(snap: dict, flightrec_base: str | None) -> dict:
    """The fleet-scoped /tracez merge: every live member's slow-trace
    ring (scraped over its metrics port) joined with every recorder
    ring file under LDT_FLIGHTREC_DIR, grouped by request id. One
    request that crossed processes (HTTP front here, shm worker there)
    renders as ONE entry whose `processes` list spans them."""
    by_id: dict = {}

    def _entry(rid: str) -> dict:
        return by_id.setdefault(
            rid, {"request_id": rid, "traces": [], "events": [],
                  "processes": []})

    def _saw(e: dict, proc: str) -> None:
        if proc not in e["processes"]:
            e["processes"].append(proc)

    for mem in snap.get("members", ()):
        port = int(mem.get("metrics_port") or 0)
        if port <= 0:
            continue
        for tr in _member_slow_traces(port):
            rid = tr.get("request_id")
            if not rid:
                continue
            e = _entry(rid)
            e["traces"].append(dict(tr, slot=mem.get("slot")))
            _saw(e, f"pid:{mem.get('pid')}")
    if flightrec_base:
        import glob
        try:
            ring_files = sorted(glob.glob(
                os.path.join(flightrec_base, "**", "flightrec-*.ring"),
                recursive=True))
        except OSError:
            ring_files = []
        for path in ring_files:
            for ev in flightrec.request_events(path):
                rid = ev.get("request_id")
                if not rid:
                    continue
                e = _entry(rid)
                e["events"].append(ev)
                _saw(e, f"pid:{ev.get('pid')}")
    entries = sorted(by_id.values(),
                     key=lambda e: -(len(e["traces"]) + len(e["events"])))
    return {"requests": entries, "count": len(entries)}


def _start_status_server(port: int, status: FleetStatus,
                         flightrec_base: str | None = None,
                         fleet_config: FleetConfig | None = None):
    """GET /fleetz (JSON control-plane view: per-member slot, pid,
    generation, state — the chaos smoke picks its SIGKILL victim here),
    GET /tracez (fleet-scoped request-id merge across member slow rings
    and recorder files), GET /metrics (ldt_fleet_* exposition) and
    POST /configz (canary-then-fan-out fleet config push,
    _fleet_config_push) on a daemon thread."""

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
            if not self.path.startswith("/configz") \
                    or fleet_config is None:
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(min(length, 65536)) if length else b""
            code, payload = _fleet_config_push(status.read(),
                                               fleet_config, raw)
            body = json.dumps(payload, indent=2).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            snap = status.read()
            if self.path.startswith("/fleetz"):
                body = json.dumps(dict(snap, slo=_fleet_slo(snap)),
                                  indent=2).encode()
                ctype = "application/json"
            elif self.path.startswith("/sloz"):
                body = json.dumps(_fleet_slo(snap), indent=2).encode()
                ctype = "application/json"
            elif self.path.startswith("/tracez"):
                body = json.dumps(
                    _fleet_traces(snap, flightrec_base),
                    indent=2).encode()
                ctype = "application/json"
            elif self.path.startswith("/metrics"):
                fams = list(telemetry.REGISTRY.families())
                fams.extend(_fleet_families(snap))
                body = telemetry.render_exposition(fams).encode()
                ctype = "text/plain; version=0.0.4"
            else:
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet: fleet logs are structured
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="fleet-status")
    t.start()
    return srv


def _read_ready(path: str) -> dict:
    try:
        with open(path) as f:
            return json.loads(f.read() or "{}")
    except (OSError, ValueError):
        return {}


def fleet_main(module: str) -> int:
    """Supervise LDT_FLEET_WORKERS members of `module`. Returns the
    exit code to propagate (0 on a clean signal-initiated drain)."""
    n = knobs.get_int("LDT_FLEET_WORKERS") or 1
    fmin = min(knobs.get_int("LDT_FLEET_MIN") or n, n)
    fmax = max(knobs.get_int("LDT_FLEET_MAX") or n, n)
    health_sec = knobs.get_float("LDT_FLEET_HEALTH_SEC") or 1.0
    degraded_fails = knobs.get_int("LDT_FLEET_DEGRADED_FAILS") or 3
    backoff_base = knobs.get_float("LDT_CRASH_BACKOFF_BASE_SEC") or 0.5
    backoff_max = knobs.get_float("LDT_CRASH_BACKOFF_MAX_SEC") or 30.0
    loop_window = knobs.get_float("LDT_CRASH_LOOP_WINDOW_SEC") or 60.0
    loop_max = knobs.get_int("LDT_CRASH_LOOP_MAX") or 5
    swap_timeout = knobs.get_float("LDT_SWAP_TIMEOUT_SEC") or 30.0
    status_port = knobs.get_int("LDT_FLEET_STATUS_PORT") or 0
    metrics_base = knobs.get_int("PROMETHEUS_PORT") or 0
    uds_base = knobs.get_str("LDT_UNIX_SOCKET")
    shm_base = knobs.get_str("LDT_SHM_DIR")
    flightrec_base = knobs.get_str("LDT_FLIGHTREC_DIR")
    capture_base = knobs.get_str("LDT_CAPTURE_DIR")
    # the fleet's own recorder lands directly under the base dir;
    # members get per-slot subdirectories (see _member_env)
    flightrec.init_from_env(role="fleet")

    control = FleetControl(
        loop_max=loop_max, loop_window=loop_window,
        cooldown_sec=(knobs.get_float("LDT_FLEET_CIRCUIT_COOLDOWN_SEC")
                      or 5.0),
        scale_hold_sec=(knobs.get_float("LDT_FLEET_SCALE_HOLD_SEC")
                        or 10.0),
        up_depth=knobs.get_int("LDT_FLEET_SCALE_UP_DEPTH") or 64,
        down_depth=knobs.get_int("LDT_FLEET_SCALE_DOWN_DEPTH") or 0)

    cache_dir = knobs.get_str("LDT_COMPILE_CACHE_DIR")
    if not cache_dir:
        cache_dir = os.path.join(
            tempfile.gettempdir(), f"ldt-compile-cache-{os.getpid()}")
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        cache_dir = None
    # one AOT bundle dir for the whole fleet (aot.py): the first member
    # to compile a ladder tier exports it, every other member — and
    # every later generation, including rolling-swap standbys — loads
    # it. SHARED across slots on purpose, unlike the per-slot shm/
    # flightrec dirs: executables are content-keyed, not owner-keyed
    aot_dir = knobs.get_str("LDT_AOT_DIR")
    if not aot_dir:
        aot_dir = os.path.join(
            tempfile.gettempdir(), f"ldt-aot-{os.getpid()}")
    try:
        os.makedirs(aot_dir, exist_ok=True)
    except OSError:
        aot_dir = None

    members: list = [FleetMember(slot) for slot in range(n)]
    desired = n
    generation = 0
    probe_slot: int | None = None
    stopping = False
    swap_requested = False
    exit_rc = 0

    def _member_env(m: FleetMember, gen: int, swapped: bool = False,
                    artifact: str | None = None) -> dict:
        env = dict(os.environ)  # ldt-lint: disable=knob-direct-env -- building the child environment, not reading config
        env["LDT_WORKER_GENERATION"] = str(gen)
        env["LDT_FLEET_SLOT"] = str(m.slot)
        # members must overlap on the listen port — with each other and
        # with their own rolling-swap standbys
        env["LDT_REUSEPORT"] = "1"
        env["LDT_READY_FILE"] = m.ready_file
        env["PROMETHEUS_PORT"] = \
            str(metrics_base + m.slot) if metrics_base > 0 else "0"
        if uds_base:
            env["LDT_UNIX_SOCKET"] = f"{uds_base}.{m.slot}"
        if shm_base:
            # per-member ring directory: each member's scan thread owns
            # its own rings, and a respawn re-attaches the same dir —
            # the generation bump fences whatever the dead member left
            shm_dir = os.path.join(shm_base, f"m{m.slot}")
            try:
                os.makedirs(shm_dir, exist_ok=True)
            except OSError:
                pass
            env["LDT_SHM_DIR"] = shm_dir
        if flightrec_base:
            # per-member recorder directory, same pattern as the shm
            # rings: the harvest path after a crash is deterministic —
            # <base>/m<slot>/flightrec-<pid>.ring
            fr_dir = os.path.join(flightrec_base, f"m{m.slot}")
            try:
                os.makedirs(fr_dir, exist_ok=True)
            except OSError:
                pass
            env["LDT_FLIGHTREC_DIR"] = fr_dir
        if capture_base:
            # per-member capture directory (same pattern): the merged
            # replay input is <base>/m<slot>/{segment-*.cap,*.ring}
            cap_dir = os.path.join(capture_base, f"m{m.slot}")
            try:
                os.makedirs(cap_dir, exist_ok=True)
            except OSError:
                pass
            env["LDT_CAPTURE_DIR"] = cap_dir
        if cache_dir:
            env["LDT_COMPILE_CACHE_DIR"] = cache_dir
        if aot_dir:
            env["LDT_AOT_DIR"] = aot_dir
        # the fleet-shared result cache must be ONE file for every
        # member, but LDT_SHM_DIR above is per-slot — pin the path
        # explicitly so members actually share (operator value wins)
        if not knobs.get_str("LDT_SHARED_CACHE_FILE"):
            env["LDT_SHARED_CACHE_FILE"] = os.path.join(
                shm_base or tempfile.gettempdir(),
                f"ldt-shared-cache-{os.getpid()}.bin")
        if swapped:
            env["LDT_SWAPPED"] = "1"
        if artifact:
            env["LDT_ARTIFACT_PATH"] = artifact
        return env

    def _new_ready_file(slot: int, gen: int) -> str:
        path = os.path.join(
            tempfile.gettempdir(),
            f"ldt-fleet-{os.getpid()}-{slot}-{gen}.json")
        try:
            os.remove(path)
        except OSError:
            pass
        return path

    def _spawn(m: FleetMember, reason: str) -> bool:
        nonlocal generation
        generation += 1
        m.ready_file = _new_ready_file(m.slot, generation)
        try:
            if faults.ACTIVE is not None:
                faults.hit("worker_spawn")
            proc = subprocess.Popen(
                [sys.executable, "-m", module],
                env=_member_env(m, generation))
        except (faults.FaultInjected, OSError) as e:
            m.next_spawn_at = time.time() + backoff_base
            _log("fleet: member spawn failed — retrying after backoff",
                 reason="spawn-failed", slot=m.slot,
                 generation=generation, error=repr(e))
            return False
        m.proc = proc
        m.generation = generation
        m.metrics_port = 0
        m.fail_streak = 0
        m.queue_docs = 0
        m.brownout = 0
        m.config_generation = 0  # fresh process: heal re-pushes
        m.last_scrape = 0.0
        m.ready_deadline = time.time() + 2 * swap_timeout
        telemetry.REGISTRY.counter_inc("ldt_fleet_spawn_total", 1,
                                       reason=reason)
        flightrec.emit_event("fleet_member_state", slot=m.slot,
                             state="spawning", reason=reason,
                             pid=proc.pid)
        _log("fleet: member spawned", reason=reason, slot=m.slot,
             generation=generation, pid=proc.pid)
        return True

    def _stop_all(signum=None) -> None:
        for m in members:
            m.signaled = _forward_stop(m.proc, m.signaled)

    # PID-1 duty at fleet scale: any stop signal triggers a graceful
    # SIGTERM drain of every member (exactly once per process via the
    # per-member latch), so `docker stop` and Ctrl+C both exit 0 once
    # every member drains cleanly.
    def _stop_handler(signum, frame):
        nonlocal stopping
        stopping = True
        _stop_all(signum)

    signal.signal(signal.SIGTERM, _stop_handler)
    signal.signal(signal.SIGINT, _stop_handler)

    def _request_swap(signum, frame):
        nonlocal swap_requested
        swap_requested = True

    if hasattr(signal, "SIGHUP"):
        signal.signal(signal.SIGHUP, _request_swap)

    status = FleetStatus()
    fleet_config = FleetConfig()
    status_srv = _start_status_server(status_port, status,
                                      flightrec_base, fleet_config) \
        if status_port > 0 else None
    postmortems: list = []  # newest-last, bounded below

    _log("fleet: starting", reason="fleet-start", workers=n,
         fleet_min=fmin, fleet_max=fmax, module=module)

    def _accepting_count() -> int:
        return sum(1 for m in members if m.accepting())

    def _backoff_for(m: FleetMember) -> float:
        b = min(backoff_base * (2 ** max(m.consec_crashes - 1, 0)),
                backoff_max)
        return b * (0.5 + random.random())  # jitter: x0.5 - x1.5

    def _harvest(m: FleetMember, pid: int | None, rc,
                 reason: str) -> None:
        """Pull the dead member's flight recorder into a postmortem:
        the crash-safe ring outlives the process, so the last events
        and the request ids still in flight survive a SIGKILL."""
        if not flightrec_base or not pid:
            return
        path = flightrec.ring_path(
            os.path.join(flightrec_base, f"m{m.slot}"), pid)
        try:
            pm = flightrec.harvest_postmortem(path, reason=reason,
                                              rc=rc)
        except (OSError, ValueError) as e:
            telemetry.REGISTRY.counter_inc("ldt_postmortem_total",
                                           result="missing")
            _log("fleet: postmortem harvest failed — no readable "
                 "recorder ring", reason="postmortem", slot=m.slot,
                 pid=pid, error=repr(e))
            return
        pm["slot"] = m.slot
        pm["generation"] = m.generation
        telemetry.REGISTRY.counter_inc("ldt_postmortem_total",
                                       result="harvested")
        flightrec.emit_event("postmortem", slot=m.slot, pid=pid,
                             rc=rc, reason=reason,
                             events_total=pm.get("events_total"),
                             inflight=len(
                                 pm.get("inflight_request_ids") or ()))
        _log("fleet: postmortem harvested", reason="postmortem",
             slot=m.slot, pid=pid, rc=rc,
             events_total=pm.get("events_total"),
             events_held=pm.get("events_held"),
             inflight_request_ids=pm.get("inflight_request_ids"))
        postmortems.append(pm)
        del postmortems[:-8]  # keep the newest 8 on /fleetz
        flightrec.discard(path)  # consumed: a respawn starts clean

    def _reap() -> None:
        nonlocal probe_slot
        for m in list(members):
            if m.proc is None:
                continue
            lost = False
            dead_pid = m.proc.pid
            rc = m.proc.poll()
            if rc is None:
                if faults.ACTIVE is not None:
                    try:
                        faults.hit("worker_lost")
                    except faults.FaultInjected:
                        # simulated silent loss: the member dies
                        # without a goodbye and the reap treats it
                        # exactly like a crash
                        m.proc.kill()
                        m.proc.wait()
                        rc = m.proc.returncode
                        lost = True
                if rc is None:
                    continue
            m.proc = None
            m.signaled = None
            now = time.time()
            if m.retiring and rc == 0:
                m.mark_dead()
                members.remove(m)
                _log("fleet: member drained for scale-down",
                     reason="scale-down-done", slot=m.slot, rc=rc)
                continue
            if rc == RECYCLE_EXIT_CODE:
                m.mark_dead()
                m.consec_crashes = 0
                m.next_spawn_at = 0.0
                _log("fleet: member recycled", reason="recycle",
                     slot=m.slot, rc=rc, generation=m.generation)
                continue
            if rc == 0:
                # unplanned-but-clean exit: respawn without crash
                # accounting (a drain we did not order, e.g. an
                # operator SIGTERMing one member by hand)
                m.mark_dead()
                m.next_spawn_at = 0.0
                _log("fleet: member exited cleanly — respawning",
                     reason="clean-exit", slot=m.slot, rc=rc)
                continue
            # crash
            m.mark_dead()
            crash_kind = "lost" if lost else "crash"
            flightrec.emit_event("fleet_member_state", slot=m.slot,
                                 state="dead", reason=crash_kind,
                                 rc=rc)
            _harvest(m, dead_pid, rc, crash_kind)
            accepting = _accepting_count()
            m.crash_times = [t for t in m.crash_times
                             if now - t <= loop_window]
            m.crash_times.append(now)
            m.consec_crashes += 1
            telemetry.REGISTRY.counter_inc(
                "ldt_fleet_worker_lost_total", 1,
                reason=crash_kind)
            if m.retiring:
                # the scale-down victim crashed instead of draining:
                # its slot is already surplus, so drop it
                members.remove(m)
                _log("fleet: retiring member crashed — removed",
                     reason="scale-down-done", slot=m.slot, rc=rc)
                continue
            if len(m.crash_times) >= loop_max:
                m.parked = True
                _log("fleet: member crash-loop — parked",
                     reason="crash-loop", slot=m.slot, rc=rc,
                     crashes=len(m.crash_times),
                     window_sec=loop_window)
            m.next_spawn_at = now + _backoff_for(m)
            if probe_slot == m.slot:
                probe_slot = None
                control.probe_failed(now)
                _log("fleet: probe member died — circuit re-opened",
                     reason="fleet-circuit-reopen", slot=m.slot, rc=rc)
            elif control.record_crash(now, accepting):
                _log("fleet: correlated crash — fleet circuit open",
                     reason="fleet-circuit-open", slot=m.slot, rc=rc,
                     crashes_in_window=len(control.crash_times),
                     accepting=accepting)
            else:
                _log("fleet: member crashed — respawn after backoff",
                     reason="crash", slot=m.slot, rc=rc,
                     consecutive_crashes=m.consec_crashes)

    def _probe_step(now: float) -> None:
        nonlocal probe_slot
        if not control.probe_due(now):
            return
        control.begin_probe()
        if _accepting_count() > 0:
            # capacity survived the correlated crash: no probe spawn
            # needed, resume normal restarts
            control.probe_ok()
            _log("fleet: circuit closed — capacity held through "
                 "cooldown", reason="fleet-circuit-close")
            return
        cand = next((m for m in members
                     if m.state == FLEET_DEAD and not m.parked
                     and not m.retiring), None)
        if cand is None:
            control.probe_failed(now)
            _log("fleet: no probe candidate (all members parked) — "
                 "operator action required",
                 reason="fleet-circuit-stuck")
            return
        probe_slot = cand.slot
        cand.next_spawn_at = 0.0
        _log("fleet: spawning half-open probe member",
             reason="fleet-probe", slot=cand.slot)

    def _spawn_step(now: float) -> None:
        for m in members:
            if m.proc is not None or m.parked or m.retiring:
                continue
            if control.circuit != CIRCUIT_CLOSED \
                    and m.slot != probe_slot:
                continue
            if now < m.next_spawn_at:
                continue
            if m.state == FLEET_DEAD:
                m.mark_restarting()
            reason = "probe" if m.slot == probe_slot else (
                "initial" if m.generation == 0 else "restart")
            if _spawn(m, reason):
                m.mark_spawning()

    def _config_heal(m: FleetMember) -> None:
        """Converge a drifted member (respawned after a crash, or one
        the fan-out missed) onto the fleet-committed config: re-push
        the committed batch with no probation and the committed
        generation stamp. The fleet's view wins — a member whose local
        generation ran ahead through direct pushes is pulled back."""
        fgen, fvalues = fleet_config.read()
        if fgen <= 0 or m.config_generation == fgen or not fvalues:
            return
        try:
            st, _resp = _member_configz(
                m.metrics_port,
                {"set": fvalues, "probation_sec": 0, "generation": fgen})
        except Exception as e:  # noqa: BLE001 - retried next scrape
            _log("fleet: config heal push failed",
                 reason="config-heal", slot=m.slot, error=repr(e))
            return
        if st == 200:
            m.config_generation = fgen
            telemetry.REGISTRY.counter_inc(
                "ldt_fleet_config_heal_total", 1)
            _log("fleet: member healed onto committed config",
                 reason="config-heal", slot=m.slot, generation=fgen)
        else:
            # e.g. 409: the member has its own probation in flight —
            # the next health scrape retries
            _log("fleet: config heal refused", reason="config-heal",
                 slot=m.slot, status=st)

    knob_version = knobs.overrides_version()

    def _refresh_control_knobs() -> None:
        """The autoscale thresholds are mutable knobs: re-derive the
        FleetControl fields when a committed push bumped the override
        version (one int compare per loop otherwise)."""
        nonlocal knob_version
        v = knobs.overrides_version()
        if v == knob_version:
            return
        knob_version = v
        control.scale_hold_sec = (
            knobs.get_float("LDT_FLEET_SCALE_HOLD_SEC") or 10.0)
        control.up_depth = knobs.get_int("LDT_FLEET_SCALE_UP_DEPTH") or 64
        control.down_depth = (
            knobs.get_int("LDT_FLEET_SCALE_DOWN_DEPTH") or 0)
        _log("fleet: autoscale knobs refreshed from committed config",
             reason="config-push", up_depth=control.up_depth,
             down_depth=control.down_depth,
             scale_hold_sec=control.scale_hold_sec)

    def _health_step(now: float) -> None:
        nonlocal probe_slot
        for m in members:
            if m.proc is None:
                continue
            if m.state == FLEET_SPAWNING:
                if os.path.exists(m.ready_file):
                    info = _read_ready(m.ready_file)
                    m.metrics_port = int(info.get("metrics_port") or 0)
                    m.mark_ready()
                    m.fail_streak = 0
                    control.bootstrapped = True
                    if probe_slot == m.slot:
                        probe_slot = None
                        control.probe_ok()
                        _log("fleet: probe member ready — circuit "
                             "closed", reason="fleet-circuit-close",
                             slot=m.slot)
                    flightrec.emit_event("fleet_member_state",
                                         slot=m.slot, state="ready")
                    _log("fleet: member ready", reason="ready",
                         slot=m.slot, generation=m.generation,
                         metrics_port=m.metrics_port)
                elif now > m.ready_deadline:
                    _log("fleet: member never became ready — killing",
                         reason="ready-timeout", slot=m.slot,
                         generation=m.generation)
                    m.proc.kill()  # the reap treats it as a crash
                continue
            if m.metrics_port <= 0:
                continue  # liveness-only member (no metrics listener)
            if now - m.last_scrape < health_sec:
                continue
            m.last_scrape = now
            ok = True
            try:
                if faults.ACTIVE is not None:
                    faults.hit("fleet_route")
                url = (f"http://127.0.0.1:{m.metrics_port}"
                       f"/debug/vars")
                with urllib.request.urlopen(url, timeout=2.0) as r:
                    d = json.loads(r.read().decode())
                adm = d.get("admission") or {}
                m.queue_docs = int(adm.get("queue_docs") or 0)
                m.brownout = int(adm.get("brownout_level") or 0)
                cfg = d.get("config") or {}
                m.config_generation = int(cfg.get("generation") or 0)
                rd = d.get("ready")
                if isinstance(rd, dict) and rd.get("ready") is False:
                    ok = False
            except Exception:
                ok = False
            if ok:
                if m.fail_streak:
                    _log("fleet: member healthy again", reason="ready",
                         slot=m.slot, fails=m.fail_streak)
                m.fail_streak = 0
                m.mark_ready()
                _config_heal(m)
            else:
                m.fail_streak += 1
                if m.fail_streak == degraded_fails:
                    flightrec.emit_event("fleet_member_state",
                                         slot=m.slot, state="degraded",
                                         fails=m.fail_streak)
                    _log("fleet: member degraded — health scrapes "
                         "failing", reason="degraded", slot=m.slot,
                         fails=m.fail_streak)
                if m.fail_streak >= degraded_fails:
                    m.mark_degraded()
                if m.fail_streak >= 3 * degraded_fails:
                    _log("fleet: member unresponsive — killing for "
                         "restart", reason="health-kill", slot=m.slot,
                         fails=m.fail_streak)
                    m.proc.kill()  # the reap respawns it

    def _roll_one(m: FleetMember, artifact: str | None) -> bool:
        """Blue/green one slot: warmed standby up, old drained, standby
        promoted in place. False aborts the remaining rolls."""
        nonlocal generation
        try:
            if faults.ACTIVE is not None:
                faults.hit("standby_spawn")
        except faults.FaultInjected as e:
            _log("fleet: roll aborted — injected fault",
                 reason="swap-abort", slot=m.slot, error=repr(e))
            return False
        generation += 1
        gen = generation
        ready_file = _new_ready_file(m.slot, gen)
        old_ready_file, m.ready_file = m.ready_file, ready_file
        standby = subprocess.Popen(
            [sys.executable, "-m", module],
            env=_member_env(m, gen, swapped=True, artifact=artifact))
        m.ready_file = old_ready_file
        telemetry.REGISTRY.counter_inc("ldt_fleet_spawn_total", 1,
                                       reason="swap")
        deadline = time.time() + swap_timeout
        ready = False
        while time.time() < deadline:
            if standby.poll() is not None:
                _log("fleet: roll aborted — standby died before ready",
                     reason="swap-abort", slot=m.slot,
                     rc=standby.returncode, standby_generation=gen)
                return False
            if os.path.exists(ready_file):
                ready = True
                break
            # ready check FIRST: a stop racing the handshake must not
            # abort a standby that already landed its ready file — the
            # promote completes and the drain loop stops the promoted
            # process (supervisor.py established the ordering)
            if stopping:
                break
            time.sleep(0.05)
        if not ready:
            standby.kill()
            standby.wait()
            _log("fleet: roll aborted — standby not ready in time",
                 reason="swap-abort", slot=m.slot,
                 standby_generation=gen, timeout_sec=swap_timeout)
            return False
        old = m.proc
        _log("fleet: roll cutover — draining old generation",
             reason="swap", slot=m.slot, generation=m.generation,
             standby_generation=gen)
        m.signaled = _forward_stop(old, m.signaled)
        try:
            old.wait(timeout=swap_timeout)
        except subprocess.TimeoutExpired:
            old.kill()
            old.wait()
        m.proc = standby
        m.generation = gen
        m.ready_file = ready_file
        m.metrics_port = int(_read_ready(ready_file)
                             .get("metrics_port") or 0)
        m.last_scrape = 0.0
        m.fail_streak = 0
        m.config_generation = 0  # promoted process: heal re-pushes
        _log("fleet: roll complete", reason="swap", slot=m.slot,
             generation=gen)
        return True

    def _rolling_swap() -> None:
        artifact = None
        pointer = knobs.get_str("LDT_ARTIFACT_POINTER")
        if pointer:
            try:
                with open(pointer) as f:
                    artifact = f.read().strip()
            except OSError as e:
                _log("fleet: rolling swap aborted — artifact pointer "
                     "unreadable", reason="swap-abort", pointer=pointer,
                     error=repr(e))
                return
        _log("fleet: rolling swap starting", reason="swap",
             members=len(members))
        for m in sorted(members, key=lambda x: x.slot):
            if stopping:
                _log("fleet: rolling swap stopped by signal",
                     reason="swap-abort", slot=m.slot)
                return
            if m.retiring or m.parked or m.proc is None:
                continue
            # the never-below-N-1-ready invariant: a roll only starts
            # while every OTHER active member is READY, so the one
            # draining slot is the only capacity briefly in flux
            others_ready = all(
                x.state == FLEET_READY for x in members
                if x is not m and not x.retiring and not x.parked)
            if m.state != FLEET_READY or not others_ready:
                _log("fleet: rolling swap aborted — fleet not fully "
                     "ready", reason="swap-abort", slot=m.slot,
                     state=STATE_NAMES.get(m.state))
                return
            if not _roll_one(m, artifact):
                return
            _reap()  # a member death during the roll heals before the
            _health_step(time.time())  # next roll's precondition check
        _log("fleet: rolling swap complete", reason="swap",
             members=len(members))

    def _autoscale_step(now: float) -> None:
        nonlocal desired
        ready = [m for m in members if m.state == FLEET_READY]
        depth = max((m.queue_docs for m in ready), default=0)
        brown = max((m.brownout for m in ready), default=0)
        delta = control.scale_delta(now, depth, brown)
        if delta > 0 and desired < fmax \
                and control.circuit == CIRCUIT_CLOSED:
            desired += 1
            slot = max((m.slot for m in members), default=-1) + 1
            members.append(FleetMember(slot))
            telemetry.REGISTRY.counter_inc("ldt_fleet_scale_total", 1,
                                           direction="up")
            _log("fleet: scaling up", reason="scale-up", slot=slot,
                 desired=desired, queue_docs=depth, brownout=brown)
        elif delta < 0 and desired > fmin:
            victim = next(
                (m for m in sorted(members, key=lambda x: -x.slot)
                 if m.state == FLEET_READY and not m.retiring), None)
            if victim is not None:
                desired -= 1
                victim.retiring = True
                # zero-drop shrink: the ordinary graceful drain (stop
                # accepting, flush in-flight, exit 0) — the reap
                # removes the member once it exits clean
                victim.signaled = _forward_stop(victim.proc,
                                                victim.signaled)
                telemetry.REGISTRY.counter_inc("ldt_fleet_scale_total",
                                               1, direction="down")
                _log("fleet: scaling down — draining member",
                     reason="scale-down", slot=victim.slot,
                     desired=desired, queue_docs=depth)

    def _snapshot() -> dict:
        fgen, fvalues = fleet_config.read()
        return {
            "members": [
                {"slot": m.slot,
                 "pid": m.proc.pid if m.proc is not None else None,
                 "generation": m.generation,
                 "state": STATE_NAMES.get(m.state, "?"),
                 "metrics_port": m.metrics_port,
                 "queue_docs": m.queue_docs,
                 "brownout": m.brownout,
                 "config_generation": m.config_generation,
                 "parked": m.parked,
                 "retiring": m.retiring}
                for m in sorted(members, key=lambda x: x.slot)],
            "config": {"generation": fgen, "values": fvalues},
            "desired": desired,
            "ready": sum(1 for m in members
                         if m.state == FLEET_READY),
            "accepting": _accepting_count(),
            "circuit": CIRCUIT_NAMES.get(control.circuit, "?"),
            "bootstrapped": control.bootstrapped,
            "postmortems": list(postmortems),
        }

    def _drain_all() -> int:
        _stop_all()
        rc = 0
        for m in members:
            if m.proc is None:
                continue
            m.signaled = _forward_stop(m.proc, m.signaled)
            try:
                r = m.proc.wait(timeout=swap_timeout)
            except subprocess.TimeoutExpired:
                m.proc.kill()
                r = m.proc.wait()
            m.mark_dead()
            if r not in (0, None) and rc == 0:
                rc = r
            _log("fleet: member stopped", reason="signal", slot=m.slot,
                 rc=r)
        _log("fleet: stopped — propagating", reason="signal", rc=rc)
        return rc

    try:
        while True:
            if stopping:
                exit_rc = _drain_all()
                return exit_rc
            now = time.time()
            _reap()
            if stopping:
                continue
            _probe_step(now)
            _spawn_step(now)
            _health_step(now)
            if swap_requested:
                swap_requested = False
                _rolling_swap()
            _refresh_control_knobs()
            _autoscale_step(now)
            status.update(_snapshot())
            try:
                time.sleep(0.05)
            except KeyboardInterrupt:  # Ctrl+C raced the handler
                continue
    finally:
        if status_srv is not None:
            status_srv.shutdown()
