// Cross-translation-unit declarations shared by packer.cc and
// epilogue.cc. Both are compiled into one libldtpack.so with C linkage,
// so a hand-copied declaration that drifted from the definition would
// compile AND link silently — this header is included by both sides to
// turn signature drift into a build error.
#pragma once
#include <cstdint>

extern "C" {

// epilogue.cc: chunk-major batched document epilogue (DocTote replay +
// close pairs + unreliable removal + summary language). out is [B, 14]
// int64 (see epilogue.cc for the lane layout).
void ldt_epilogue_flat(const int32_t* rows, const int64_t* doc_chunk_start,
                       const int32_t* n_chunks, const int32_t* direct,
                       const int32_t* text_bytes, const uint8_t* skip,
                       int32_t B, int32_t D, int32_t flags,
                       const int32_t* close_set, const int32_t* closest_alt,
                       const uint8_t* is_figs, int32_t n_lang,
                       int64_t* out);

}  // extern "C"
