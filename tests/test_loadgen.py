"""Tests for the seeded synthetic load model (loadgen.py).

The contract under test: identical (scenario, seed, params) calls are
byte-identical; distinct seeds produce distinct schedules that still
conserve the rate envelope (same count, same span, same per-interval
arrival counts up to stratification jitter); and each scenario's
signature shape is actually present (the flash crowd really steps
x10, the bursts really alternate, the hot tenant really rotates).
"""
from __future__ import annotations

import json

import pytest

from language_detector_tpu import loadgen

N = 800


@pytest.mark.parametrize("scenario", loadgen.scenario_names())
def test_same_seed_is_byte_identical(scenario):
    a = loadgen.generate(scenario, n=N, seed=7)
    b = loadgen.generate(scenario, n=N, seed=7)
    assert json.dumps(a, sort_keys=True) == json.dumps(b,
                                                       sort_keys=True)


@pytest.mark.parametrize("scenario", loadgen.scenario_names())
def test_distinct_seeds_distinct_but_rate_conserving(scenario):
    a = loadgen.generate(scenario, n=N, seed=7)
    b = loadgen.generate(scenario, n=N, seed=8)
    assert json.dumps(a) != json.dumps(b), "seed had no effect"
    assert len(a) == len(b) == N
    # same span (stratified inverse-CDF arrivals pin the envelope)
    span_a = max(r["arrival_ns"] for r in a)
    span_b = max(r["arrival_ns"] for r in b)
    assert abs(span_a - span_b) / max(span_a, 1) < 0.02
    # same per-interval arrival counts, up to one request of
    # stratification jitter per bucket edge
    ca = loadgen.interval_counts(a, buckets=10)
    cb = loadgen.interval_counts(b, buckets=10)
    for i, (x, y) in enumerate(zip(ca, cb)):
        assert abs(x - y) <= 3, (i, ca, cb)


@pytest.mark.parametrize("scenario", loadgen.scenario_names())
def test_records_use_capture_shape(scenario):
    """Replayability: records must be indistinguishable from
    merge_captures() output — the replay driver asserts nothing, so
    the shape check lives here."""
    recs = loadgen.generate(scenario, n=32, seed=1)
    prev = -1
    for r in recs:
        assert r["arrival_ns"] >= prev  # sorted schedule
        prev = r["arrival_ns"]
        assert r["docs"] >= 1
        assert r["approx_bytes"] >= 64
        assert isinstance(r["tenant"], str)
        assert isinstance(r["tenant_hash"], int)
        assert isinstance(r["priority"], bool)
        assert r["verdict"] == "ok"


def test_flash_crowd_steps_x10():
    recs = loadgen.generate("flash_crowd", n=2000, seed=3)
    counts = loadgen.interval_counts(recs, buckets=10)
    base = sum(counts[:4]) / 4
    crowd = sum(counts[4:7]) / 3
    assert crowd / base == pytest.approx(loadgen.FLASH_FACTOR,
                                         rel=0.15)


def test_burst_lull_alternates():
    recs = loadgen.generate("burst_lull", n=2000, seed=3)
    counts = loadgen.interval_counts(recs, buckets=10)
    bursts = counts[0::2]
    lulls = counts[1::2]
    assert min(bursts) > max(lulls)


def test_diurnal_peaks_mid_span():
    recs = loadgen.generate("diurnal", n=2000, seed=3)
    counts = loadgen.interval_counts(recs, buckets=10)
    assert max(counts[4:6]) == max(counts)
    assert min(counts) == min(counts[0], counts[-1])


def test_tenant_shift_rotates_hot_tenant():
    recs = loadgen.generate("tenant_shift", n=3000, seed=3,
                            tenants=32)
    span = max(r["arrival_ns"] for r in recs) + 1

    def hot(third):
        seen: dict = {}
        for r in recs:
            if int(r["arrival_ns"] * 3 / span) == third:
                seen[r["tenant"]] = seen.get(r["tenant"], 0) + 1
        return max(seen, key=seen.get)

    hots = [hot(i) for i in range(3)]
    assert len(set(hots)) == 3, f"hot tenant never rotated: {hots}"


def test_unknown_scenario_raises():
    with pytest.raises(KeyError):
        loadgen.generate("no-such-shape")


def test_base_rate_scales_span():
    """Doubling base_rps halves the span — intensity 1.0 regions run
    at exactly base_rps."""
    a = loadgen.generate("tenant_shift", n=500, seed=1,
                         base_rps=100.0)
    b = loadgen.generate("tenant_shift", n=500, seed=1,
                         base_rps=200.0)
    span_a = max(r["arrival_ns"] for r in a)
    span_b = max(r["arrival_ns"] for r in b)
    assert span_a / span_b == pytest.approx(2.0, rel=0.01)
