"""Device/batched engine vs scalar engine agreement.

The batched TPU path (preprocess/pack.py -> ops/score.py -> host epilogue in
models/ngram.py) must produce byte-identical results to the scalar engine
(engine_scalar.py, itself oracle-parity-tested) on every document: the 402
reference golden paragraphs, randomized mixed-script composites, and the
fallback/edge paths (spam squeezing, empty and tiny inputs).

Batches reuse the small chunk-major bucket shapes so the scoring program
compiles once per session (cached persistently in .jax_cache/).
"""
import random
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent))
from golden_data import golden_pairs  # noqa: E402

BATCH = 64


@pytest.fixture(scope="session")
def engine():
    from language_detector_tpu.models.ngram import NgramBatchEngine
    return NgramBatchEngine()


def _result_tuple(r):
    return (r.summary_lang, tuple(r.language3), tuple(r.percent3),
            tuple(r.normalized_score3), r.text_bytes, r.is_reliable)


def _assert_batch_agrees(engine, texts):
    from language_detector_tpu.engine_scalar import detect_scalar
    padded = texts + [""] * (-len(texts) % BATCH)
    got = []
    for i in range(0, len(padded), BATCH):
        got.extend(engine.detect_batch(padded[i:i + BATCH]))
    bad = []
    for i, t in enumerate(texts):
        want = detect_scalar(t, engine.tables, engine.reg)
        if _result_tuple(got[i]) != _result_tuple(want):
            bad.append((i, t[:60], _result_tuple(got[i]),
                        _result_tuple(want)))
    assert not bad, f"{len(bad)} disagreements, first: {bad[0]}"


def _golden_texts():
    pairs = golden_pairs()
    if not pairs:
        pytest.skip("reference snapshot unavailable")
    return [t.decode("utf-8", errors="replace") for _, _, t in pairs]


def test_golden_agreement(engine):
    """Device == scalar on every reference golden paragraph."""
    _assert_batch_agrees(engine, _golden_texts())


def test_random_mixed_script_agreement(engine):
    """Composites spliced from random golden fragments: multi-span,
    multi-script documents, including CJK+Latin mixes."""
    texts = _golden_texts()
    rng = random.Random(20260729)
    docs = []
    for _ in range(BATCH):
        parts = []
        for _ in range(rng.randint(1, 4)):
            t = texts[rng.randrange(len(texts))]
            lo = rng.randrange(max(1, len(t) - 200))
            parts.append(t[lo:lo + rng.randint(40, 200)])
        docs.append(" ".join(parts))
    _assert_batch_agrees(engine, docs)


def test_squeeze_spam_agreement(engine):
    """Squeeze-trigger (repetitive) documents stay on the device path: the
    native packer performs the squeeze re-scan itself (packer.cc
    squeeze_span, mirroring the reference's recursive kCLDFlagSqueeze
    pass) and still agrees with the scalar engine end-to-end."""
    from language_detector_tpu import native
    spam = ("buy cheap now " * 400).strip()
    docs = [spam, "word " * 600, "The quick brown fox. " + "spam ham " * 300]
    cb = native.pack_chunks_native(docs, engine.tables, engine.reg)
    assert not cb.fallback.any(), \
        "squeeze docs must pack natively, not fall back"
    _assert_batch_agrees(engine, docs)


def test_edge_inputs_agreement(engine):
    """Empty, whitespace, single-char, digits, emoji, long-word inputs."""
    docs = ["", " ", "\n\t ", "a", "123 456 789", "!!! ??? ...",
            "🎉🎊🎈 🎉🎊🎈", "x" * 300,
            "word " + "a" * 50 + " end",
            "Ceci est un petit texte en français pour vérifier les accents."]
    _assert_batch_agrees(engine, docs)


def test_gate_failure_recursion_agreement(engine):
    """Documents failing the good-answer gate (impl.cc:1978-1991) take the
    scalar recursion and still agree."""
    texts = _golden_texts()
    # Mixed-language composites routinely land under the 70%/93% gates.
    docs = [texts[i][:150] + " " + texts[(i * 7 + 3) % len(texts)][:150]
            for i in range(0, 48)]
    _assert_batch_agrees(engine, docs)


def test_chunk_level_parity(engine):
    """Device chunk summaries == the scalar engine's DocTote.add sequence.

    Sharper than end-to-end agreement: catches probe/summary bugs that
    cancel out in document totals (e.g. a missing table lookup on one
    record kind). Covers CJK (uni+bigram), Latin (quad+octa) and
    mixed-script documents."""
    import numpy as np
    from language_detector_tpu.engine_scalar import (DocTote, ScoringContext,
                                                     score_one_span)
    from language_detector_tpu.preprocess.segment import segment_text

    from language_detector_tpu import native

    texts = _golden_texts()
    rng = random.Random(7)
    docs = [t for t in (texts[i] for i in range(0, len(texts), 9))][:48]
    docs += [texts[3][:120] + " " + texts[-5][:120] for _ in range(4)]
    docs += [""] * (-len(docs) % BATCH)

    cb = native.pack_chunks_native(docs, engine.tables, engine.reg,
                                   flags=engine.flags)
    out = engine.score_chunk_batch(cb)

    class RecordingTote(DocTote):
        def __init__(self):
            super().__init__()
            self.adds = []

        def add(self, lang, nbytes, score, reliability):
            self.adds.append((lang, nbytes, score, reliability))
            super().add(lang, nbytes, score, reliability)

    for b, text in enumerate(docs):
        if cb.fallback[b]:
            continue
        tote = RecordingTote()
        ctx = ScoringContext(tables=engine.tables, registry=engine.reg)
        for span in segment_text(text, engine.tables):
            if span.text_bytes <= 1 and \
                    engine.reg.rtype(span.ulscript) not in (0, 1):
                continue
            score_one_span(ctx, span, tote)
        direct = {int(cid): (int(lang), int(nb))
                  for cid, lang, nb in cb.direct_adds[b] if cid >= 0}
        got = []
        g0 = int(cb.doc_chunk_start[b])
        for c in range(int(cb.n_chunks[b])):
            if c in direct:
                lang, nb = direct[c]
                got.append((lang, nb, nb, 100))
            elif out[g0 + c, 4]:
                got.append(tuple(int(x) for x in out[g0 + c, :4]))
        assert got == tote.adds, \
            f"doc {b}: {got[:6]} != {tote.adds[:6]} ({text[:50]!r})"


def test_detect_many_matches_detect_batch(engine):
    """The pipelined multi-batch entry point (fetch thread + pend
    rotation) returns exactly what per-batch detection returns, in order,
    including a final partial chunk and fallback/gate-failing docs."""
    texts = _golden_texts()[:100] + ["", "tiny", "a b " * 400]
    want = []
    for i in range(0, len(texts), BATCH):
        want.extend(engine.detect_batch(texts[i:i + BATCH]))
    got = engine.detect_many(texts, batch_size=BATCH)
    assert len(got) == len(texts)
    assert [_result_tuple(r) for r in got] == \
        [_result_tuple(r) for r in want]


def _fuzz_docs(n: int, seed: int = 20260730) -> list:
    rng = random.Random(seed)
    texts = _golden_texts()
    docs: list = []
    _fill_fuzz_docs(docs, rng, texts, n)
    return docs


def test_fuzz_mixed_traffic_agreement(engine):
    """Randomized traffic soup: slices and concatenations of golden text
    across scripts, plus spam runs, entities, punctuation storms, and
    random Unicode — every construction the packer's special paths
    (squeeze, rounds, direct adds, boosts) can hit, asserted
    doc-for-doc against the scalar engine."""
    rng = random.Random(20260730)
    texts = _golden_texts()
    docs = []
    _fill_fuzz_docs(docs, rng, texts, 160)
    _assert_batch_agrees(engine, docs)


def _fill_fuzz_docs(docs, rng, texts, n):
    for i in range(n):
        kind = i % 8
        if kind == 0:    # cross-script concatenation
            docs.append(" ".join(
                texts[rng.randrange(len(texts))][:rng.randint(20, 300)]
                for _ in range(rng.randint(1, 5))))
        elif kind == 1:  # repetitive spam of a random snippet
            snip = texts[rng.randrange(len(texts))][:rng.randint(5, 30)]
            docs.append((snip + " ") * rng.randint(50, 300))
        elif kind == 2:  # mid-codepoint slices (invalid boundaries ok)
            t = texts[rng.randrange(len(texts))]
            lo = rng.randrange(max(1, len(t) - 100))
            docs.append(t[lo:lo + rng.randint(1, 80)])
        elif kind == 3:  # punctuation / digit storms
            docs.append(" ".join(
                rng.choice(["!!!", "123", "...", "@x", "#tag", "???"])
                for _ in range(rng.randint(1, 40))))
        elif kind == 4:  # random BMP codepoints
            docs.append("".join(
                chr(rng.choice([rng.randrange(0x20, 0x2000),
                                rng.randrange(0x3040, 0x9FFF)]))
                for _ in range(rng.randint(1, 120))))
        elif kind == 5:  # words glued without spaces
            t = texts[rng.randrange(len(texts))]
            docs.append(t.replace(" ", "")[:rng.randint(10, 400)])
        elif kind == 6:  # long multi-paragraph
            docs.append(" ".join(
                texts[(i * 13 + j * 7) % len(texts)][:400]
                for j in range(rng.randint(4, 12))))
        else:            # whitespace-heavy
            t = texts[rng.randrange(len(texts))][:200]
            docs.append(t.replace(" ", "   \n\t "))


def test_hinted_detection_agreement(engine):
    """Hints through the DEVICE path: prior boosts ride the wire as
    hint-window slots, whacks as per-chunk masks — results must equal
    the scalar engine with the same CLDHints on every document."""
    from language_detector_tpu.engine_scalar import detect_scalar
    from language_detector_tpu.hints import CLDHints

    reg = engine.reg
    texts = _golden_texts()
    docs = [texts[i][:300] for i in range(0, 60, 3)]
    docs += ["", "tiny", texts[2][:150] + " " + texts[-3][:150]]
    for hints in (CLDHints(tld_hint="fr"),
                  CLDHints(content_language_hint="de,en"),
                  # unique close-set member -> close-set whacks
                  CLDHints(language_hint=reg.code_to_lang["id"]),
                  CLDHints(encoding_hint="ISO_8859_8"),  # Hebrew prior
                  CLDHints(tld_hint="jp",
                           language_hint=reg.code_to_lang["no"])):
        got = engine.detect_batch(docs, hints=hints)
        for t, r in zip(docs, got):
            want = detect_scalar(t, engine.tables, engine.reg,
                                 hints=hints)
            assert _result_tuple(r) == _result_tuple(want), \
                (hints, t[:40])


def test_html_detection_agreement(engine):
    """is_plain_text=False through the DEVICE path: the host HTML
    pre-pass + lang= tag hints must reproduce the scalar engine's HTML
    handling exactly."""
    from language_detector_tpu.engine_scalar import detect_scalar

    texts = _golden_texts()
    docs = [
        "<html><body><p>" + texts[0][:200] + "</p><p>" +
        texts[0][200:400] + "</p></body></html>",
        "<div lang=\"fr\">" + texts[5][:250] + "</div>",
        "<a href='http://x'>link</a> " + texts[9][:300],
        "&eacute;t&eacute; " + texts[5][:200],
        "<script>var x = 1;</script>" + texts[12][:250],
        "<html lang='ja'><b>" + texts[3][:200] + "</b></html>",
        "plain text no markup at all " + texts[7][:200],
        "<p></p>",
        "",
    ]
    got = engine.detect_batch(docs, is_plain_text=False)
    for t, r in zip(docs, got):
        want = detect_scalar(t, engine.tables, engine.reg,
                             is_plain_text=False)
        assert _result_tuple(r) == _result_tuple(want), t[:60]


def test_lone_surrogate_inputs(engine):
    """Python strings can carry lone surrogates (e.g. surrogatepass-
    decoded byte input); both engines must detect them as non-letters —
    not crash on strict UTF-32/UTF-8 encodes — and agree."""
    docs = [
        "hello \udcd9 world this is english text with a stray surrogate",
        "𐀀 le gouvernement a annoncé de nouvelles mesures",
        "\udfff" * 20,
        "こんにちは\ud912世界、今日はとても良い天気ですね",
    ]
    _assert_batch_agrees(engine, docs)
    # HTML path, >8KB so the lang-tag scanner's byte-budget slice runs
    from language_detector_tpu.engine_scalar import detect_scalar
    big_html = ("<html lang='fr'><p>" +
                ("le monde est grand \udcd9 " * 600) + "</p></html>")
    got = engine.detect_batch([big_html], is_plain_text=False)
    want = detect_scalar(big_html, engine.tables, engine.reg,
                         is_plain_text=False)
    assert _result_tuple(got[0]) == _result_tuple(want)


def test_fuzz_multi_slice_deferred_retry(engine):
    """The cross-slice deferred gate-retry (detect_many/_detect_stream)
    must answer exactly like the single-slice path: run the fuzz corpus
    at a batch size that forces many slices (retries collect globally,
    one batched recursion pass) and compare against one-call codes."""
    docs = _fuzz_docs(96, seed=20260731)
    want = [engine.reg.code(r.summary_lang)
            for r in engine.detect_batch(docs)]
    got = engine.detect_codes(docs, batch_size=13)  # ragged multi-slice
    assert got == want


def test_slices_invariants(engine):
    """_slices guards the device memory bound: order-preserving, every
    slice within the doc-count cap, every multi-doc slice within the
    content budget (a single oversized doc may stand alone), and
    balanced — no 3M + runt split of a 4.3M stream."""
    rng = random.Random(7)
    budget = engine.DISPATCH_CHAR_BUDGET
    for case in range(6):
        if case == 0:
            docs = ["x" * rng.randint(50, 300) for _ in range(5000)]
        elif case == 1:
            docs = ["y" * rng.randint(1, 40000) for _ in range(300)]
        elif case == 2:
            docs = ["z" * (budget + 1000)]  # single over-budget doc
        elif case == 3:
            docs = []
        elif case == 4:
            docs = ["", "", "a"]
        else:
            docs = ["w" * rng.randint(100, 9000) for _ in range(2000)]
        slices = list(engine._slices(docs, 1024))
        flat = [t for s in slices for t in s]
        assert flat == docs  # order + completeness
        total = sum(len(t) for t in docs)
        n_min = max(-(-total // budget), 1)
        for s in slices:
            assert len(s) <= 1024
            vol = sum(len(t) for t in s)
            assert vol <= budget or len(s) == 1
            if docs:
                # balance: no slice exceeds the even share by more
                # than one document's worth
                assert vol <= -(-total // n_min) + max(
                    (len(t) for t in docs), default=0)
