"""Per-span output bit-identity: device lane vs scalar oracle.

The LDT_SPANS surface answers every document with `.spans` records
(byte_offset, byte_len, iso_code, percent, reliable) tiling the
document bytes. The contract (docs/ACCURACY.md) is BIT-identity, not
approximate agreement: the device lane (models/ngram.py detect_spans —
split, one flat pack, unmerged per-sub-doc epilogue) must emit exactly
the records the scalar oracle (engine_scalar.detect_scalar_spans)
emits, on every document of a multi-script corpus, including the docs
whose sub-documents fall back or fail the gate. And when spans are NOT
requested, nothing may change: span-less service responses stay
byte-identical with the knob off.
"""
from __future__ import annotations

import json
import os

import pytest

from language_detector_tpu.engine_scalar import (SPAN_SPLIT_SLOTS,
                                                 detect_scalar,
                                                 detect_scalar_spans)
from language_detector_tpu.evalsuite import corpus_pairs
from language_detector_tpu.registry import registry


@pytest.fixture(scope="module")
def eng():
    from language_detector_tpu.models.ngram import NgramBatchEngine
    return NgramBatchEngine()


def _span_corpus() -> list:
    """>= 100 multi-script docs: the eval corpus plus cross-script
    concatenations (the docs that actually produce multiple spans)."""
    pairs = corpus_pairs()
    texts = [t for _, t in pairs][:90]
    by_code = dict(pairs)
    mixes = [("en", "ru"), ("fr", "ja"), ("de", "ar"), ("es", "el"),
             ("it", "zh"), ("pt", "iw"), ("nl", "th"), ("sv", "ko"),
             ("pl", "hi"), ("tr", "uk")]
    for a, b in mixes:
        texts.append(by_code[a] + " " + by_code[b])
        texts.append(by_code[b] + " " + by_code[a] + " " + by_code[a])
    texts += ["", "a", "   ", "é"]
    assert len(texts) >= 100
    return texts


def _records(r):
    return (r.summary_lang, tuple(r.language3), tuple(r.percent3),
            tuple(r.normalized_score3), r.is_reliable, r.text_bytes,
            tuple(tuple(s) for s in (r.spans or [])))


def test_device_spans_bit_identical_to_scalar(eng):
    """The acceptance gate: >= 100-doc multi-script corpus, every span
    record and every summary field identical between the device lane
    and the scalar oracle."""
    texts = _span_corpus()
    got = eng.detect_spans(texts)
    assert len(got) == len(texts)
    for text, r in zip(texts, got):
        want = detect_scalar_spans(text, eng.tables, eng.reg,
                                   eng.flags)
        assert _records(r) == _records(want), text[:60]


def test_spans_tile_document_bytes(eng):
    """Spans are a partition of the document's bytes: offsets start at
    0, are contiguous, and sum to the UTF-8 length."""
    texts = _span_corpus()
    for text, r in zip(texts, eng.detect_spans(texts)):
        spans = r.spans or []
        nbytes = len(text.encode("utf-8"))
        if nbytes == 0:
            continue
        assert spans, text[:60]
        pos = 0
        for off, ln, code, pct, rel in spans:
            assert off == pos and ln > 0
            assert isinstance(code, str) and 0 <= pct <= 100
            assert isinstance(rel, bool)
            pos += ln
        assert pos == nbytes


def test_small_budget_forces_splits_and_stays_identical(eng):
    """A tiny per-sub-doc chunk budget forces every long doc through
    the split path (multiple sub-docs -> multiple spans) without
    perturbing the records: both engines split at the same exact span
    boundaries, so identity must survive any budget."""
    from language_detector_tpu.models.ngram import NgramBatchEngine
    small = NgramBatchEngine(eng.tables, eng.reg,
                             longdoc_chunk_slots=8)
    pairs = corpus_pairs()
    by_code = dict(pairs)
    texts = [(by_code["en"] + " " + by_code["ru"]) * 2,
             (by_code["ja"] + by_code["fr"]) * 3,
             by_code["ar"] + " " + by_code["el"] + " " + by_code["de"]]
    for text in texts:
        r = small.detect_spans([text])[0]
        want = detect_scalar_spans(text, eng.tables, eng.reg,
                                   eng.flags, 8)
        assert _records(r) == _records(want)
        assert len(r.spans) > 1  # the budget actually split


def test_span_summary_matches_unsplit_answer(eng):
    """The whole-document summary riding a spans result is the same
    verdict the plain (unsplit) path gives — the longdoc-lane merge
    invariant surfaced through detect_spans."""
    texts = [t for _, t in corpus_pairs()][:30]
    got = eng.detect_spans(texts)
    for text, r in zip(texts, got):
        want = detect_scalar(text, eng.tables, eng.reg, eng.flags)
        assert r.summary_lang == want.summary_lang
        assert r.language3 == want.language3
        assert r.percent3 == want.percent3


def test_spans_off_responses_byte_identical(monkeypatch):
    """LDT_SPANS=0 (or an un-flagged frame) answers with the exact
    bytes the pre-span service produced: the span lane may not perturb
    the default wire path."""
    from language_detector_tpu.service import wire
    from language_detector_tpu.service.server import DetectorService
    monkeypatch.delenv("LDT_SPANS", raising=False)
    svc = DetectorService(use_device=False)
    body = json.dumps({"request": [
        {"text": "hello world this is plainly english text"},
        {"text": "bonjour le monde ceci est une phrase"},
    ]}).encode()
    s_plain, c_plain = wire.handle_frame(svc, body, want_spans=False)
    # flag set but knob off: byte-identical
    s_flag, c_flag = wire.handle_frame(svc, body, want_spans=True)
    assert s_flag == s_plain
    assert b"".join(c_flag) == b"".join(c_plain)
    assert b"spans" not in b"".join(c_plain)
    # knob on + flag: spans field appears, same verdict codes
    monkeypatch.setenv("LDT_SPANS", "1")
    s_on, c_on = wire.handle_frame(svc, body, want_spans=True)
    assert s_on == s_plain
    payload = json.loads(b"".join(c_on))
    plain = json.loads(b"".join(c_plain))
    for r_on, r_off in zip(payload["response"], plain["response"]):
        spans = r_on.pop("spans")
        assert r_on == r_off
        assert spans and spans[0][0] == 0
    # knob on but frame un-flagged: still byte-identical
    s_noflag, c_noflag = wire.handle_frame(svc, body, want_spans=False)
    assert b"".join(c_noflag) == b"".join(c_plain)


def test_frame_spans_flag_roundtrip():
    """FRAME_SPANS rides the v2 frame extension; span-less pack_frame
    calls still emit the v1 short form."""
    from language_detector_tpu.service import wire
    v1 = wire.pack_frame(b"x")
    v2 = wire.pack_frame(b"x", spans=True)
    assert v1 != v2
    assert len(v1) < len(v2)  # v1 short form kept when spans unset


def test_detector_surface_spans(eng):
    """LanguageDetector.detect_spans surfaces the records through the
    public DetectionResult."""
    from language_detector_tpu.detector import LanguageDetector
    det = LanguageDetector(eng.tables, eng.reg)
    det._batch_engine = eng
    texts = ["hello world this is english text ok",
             "это русское предложение о языках"]
    rs = det.detect_spans(texts)
    for text, r in zip(texts, rs):
        assert r.spans and r.spans[0][0] == 0
        assert sum(s[1] for s in r.spans) == len(text.encode("utf-8"))
