"""Clean twin of future_bad.py: every creation resolves or escapes on
every normal exit; the consumer's broad handler fails the batch."""
from concurrent.futures import Future


def resolve_both_branches(cond):
    fut = Future()
    if cond:
        fut.set_result(1)
    else:
        fut.set_exception(RuntimeError("no"))
    return None


def escape_to_queue(q, texts):
    fut = Future()
    q.put((texts, fut))
    return fut


def raise_before_escape():
    # nothing holds a reference yet: the caller sees the exception,
    # not a hung future
    fut = Future()
    raise RuntimeError("rejected before enqueue")


def defer_to_closure(schedule):
    fut = Future()

    def _done(v):
        fut.set_result(v)

    schedule(_done)
    return fut


class Consumer:
    @staticmethod
    def _fail(pending, err):
        for fut in pending:
            if not fut.done():
                fut.set_exception(err)

    def _drain(self, q):
        pending = []
        while True:
            try:
                pending.append(q.get_nowait())
            except Exception as e:
                self._fail(pending, e)
                return
