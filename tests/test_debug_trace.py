"""Score-trace debugging (debug.py): the debug.cc dump equivalent."""
from language_detector_tpu.debug import format_trace, trace_detect


def test_trace_records_pipeline(base_tables):
    tr = trace_detect("This is English mixed with 日本語のテキストです。",
                      tables=base_tables)
    kinds = [k for k, _ in tr.events]
    assert "pass" in kinds and "span" in kinds and "chunk" in kinds
    assert kinds.count("doc_tote") >= 2  # scored + refined stages
    assert kinds[-1] == "summary"
    text = format_trace(tr)
    assert "span script=" in text and "doc_tote[scored]" in text
    # tracing must not change the result
    from language_detector_tpu.engine_scalar import detect_scalar
    plain = detect_scalar("This is English mixed with 日本語のテキストです。",
                          base_tables)
    assert tr.result.summary_lang == plain.summary_lang
    assert tr.result.percent3 == plain.percent3


def test_trace_recursion_passes(base_tables):
    # squeeze-trigger text: the trace shows both detection passes
    tr = trace_detect("ελληνικά γλώσσα είναι " * 60, tables=base_tables)
    passes = [p["flags"] for k, p in tr.events if k == "pass"]
    assert len(passes) >= 2 and any(f & 2 for f in passes)  # FLAG_SQUEEZE


def test_cli_harness(capsys):
    from language_detector_tpu.debug import _main
    assert _main(["--quiet", "--vector",
                  "国民の大多数が内閣を支持し ελληνικά γλώσσα"]) == 0
    out = capsys.readouterr().out
    assert "=>" in out and "ja" in out


def test_format_trace_html():
    """html=True renders the per-chunk colored dump (the kCLDFlagHtml
    debug render, debug.cc): every chunk decision appears as a cell and
    the page is self-contained HTML."""
    from language_detector_tpu.debug import format_trace, trace_detect
    tr = trace_detect(
        "Le gouvernement a annoncé de nouvelles mesures pour aider "
        "les familles. こんにちは世界。今日はとても良い天気ですね。")
    page = format_trace(tr, html=True)
    assert page.startswith("<!doctype html>")
    n_chunks = sum(1 for k, _ in tr.events if k == "chunk")
    assert n_chunks > 0 and page.count("class=chunk") == n_chunks
    assert "summary" in page and "doc_tote" in page
    # language codes render in the cells
    assert "fr" in page and "ja" in page
